"""Sequence layer functions — the reference's LoD-consuming layers
(dynamic_lstm nn.py:277, dynamic_gru nn.py:609, sequence_pool, sequence_conv,
sequence_expand, sequence_first_step/last_step) on the padded+lengths
representation.

Convention: a data var with lod_level > 0 is a padded dense tensor [N, T, ...]
with a companion int32 lengths var named `<name>@LEN` (created by layers.data,
fed by DataFeeder). Layers propagate the companion through sequence-preserving
ops via `Variable._seq_lengths`.
"""
from __future__ import annotations

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "dynamic_lstm", "dynamic_gru", "sequence_pool", "sequence_conv",
    "sequence_expand", "sequence_first_step", "sequence_last_step",
    "sequence_softmax", "sequence_reshape", "sequence_concat", "seq_lengths_of",
    "linear_chain_crf", "crf_decoding", "lod_reset",
    "dynamic_lstmp", "ctc_greedy_decoder",
    "gru_unit", "sequence_mask", "batch_gather", "beam_search",
    "beam_search_decode",
]

LEN_SUFFIX = "@LEN"


def seq_lengths_of(var: Variable):
    """Resolve the lengths companion of a sequence var (or None)."""
    direct = getattr(var, "_seq_lengths", None)
    if direct is not None:
        return direct
    block = var.block
    name = var.name + LEN_SUFFIX
    return block._var_recursive(name)


def _propagate_lengths(src: Variable, dst: Variable):
    lens = seq_lengths_of(src)
    if lens is not None:
        dst._seq_lengths = lens
    return dst


def dynamic_lstm(input, size, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", param_attr=None, bias_attr=None,
                 dtype="float32", name=None):
    """reference layers/nn.py:277 — input is the x-projection [N, T, 4H]."""
    helper = LayerHelper("lstm", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    size = size // 4
    weight = helper.create_parameter(helper.param_attr, shape=[size, 4 * size],
                                     dtype=dtype)
    bias_size = 7 * size if use_peepholes else 4 * size
    bias = helper.create_parameter(helper.bias_attr, shape=[bias_size],
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_cell_pre = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    lens = seq_lengths_of(input)
    if lens is not None:
        inputs["Lengths"] = [lens]
    helper.append_op(
        type="lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell],
                 "BatchGate": [batch_gate],
                 "BatchCellPreAct": [batch_cell_pre]},
        attrs={
            "use_peepholes": use_peepholes, "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    _propagate_lengths(input, hidden)
    _propagate_lengths(input, cell)
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32"):
    """reference layers/nn.py:609 — input is the x-projection [N, T, 3H]."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(helper.param_attr, shape=[size, 3 * size],
                                     dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr, shape=[3 * size],
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    bg = helper.create_variable_for_type_inference(dtype)
    brh = helper.create_variable_for_type_inference(dtype)
    bh = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    lens = seq_lengths_of(input)
    if lens is not None:
        inputs["Lengths"] = [lens]
    helper.append_op(
        type="gru",
        inputs=inputs,
        outputs={"Hidden": [hidden], "BatchGate": [bg],
                 "BatchResetHiddenPrev": [brh], "BatchHidden": [bh]},
        attrs={
            "is_reverse": is_reverse, "gate_activation": gate_activation,
            "activation": candidate_activation,
        },
    )
    _propagate_lengths(input, hidden)
    return hidden


def _seq_pool(input, pool_type):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    max_index = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [input]}
    lens = seq_lengths_of(input)
    if lens is not None:
        inputs["Lengths"] = [lens]
    helper.append_op(
        type="sequence_pool",
        inputs=inputs,
        outputs={"Out": [out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_pool(input, pool_type):
    return _seq_pool(input, pool_type)


def sequence_first_step(input):
    return _seq_pool(input, "first")


def sequence_last_step(input):
    return _seq_pool(input, "last")


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    """reference layers/nn.py sequence_conv."""
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    dtype = input.dtype
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [input], "Filter": [filter_param]}
    lens = seq_lengths_of(input)
    if lens is not None:
        inputs["Lengths"] = [lens]
    helper.append_op(
        type="sequence_conv",
        inputs=inputs,
        outputs={"Out": [pre_bias]},
        attrs={
            "contextStride": filter_stride,
            "contextStart": -int(filter_size // 2),
            "contextLength": filter_size,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=2)
    out = helper.append_activation(pre_act)
    _propagate_lengths(input, out)
    return out


def sequence_expand(x, y, ref_level=-1):
    helper = LayerHelper("sequence_expand")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]}, attrs={"ref_level": ref_level},
    )
    _propagate_lengths(y, out)
    return out


def sequence_softmax(input, use_cudnn=True):
    helper = LayerHelper("sequence_softmax")
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input]}
    lens = seq_lengths_of(input)
    if lens is not None:
        inputs["Lengths"] = [lens]
    helper.append_op(
        type="sequence_softmax", inputs=inputs, outputs={"Out": [out]},
    )
    _propagate_lengths(input, out)
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_reshape", inputs={"X": [input]},
        outputs={"Out": [out]}, attrs={"new_dim": new_dim},
    )
    return out


def sequence_reverse(x, name=None):
    """Reverse each sequence's valid prefix (reference
    paddle/fluid/operators/sequence_reverse_op.h); padding stays put, so
    the output shares x's lengths companion."""
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    lens = seq_lengths_of(x)
    if lens is not None:
        inputs["Lengths"] = [lens]
    helper.append_op(type="sequence_reverse", inputs=inputs,
                     outputs={"Out": [out]})
    _propagate_lengths(x, out)
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    lens = [seq_lengths_of(v) for v in input]
    inputs = {"X": input}
    if any(l is not None for l in lens):
        if any(l is None for l in lens):
            raise ValueError(
                "sequence_concat: either all inputs carry lengths or none"
            )
        inputs["Lengths"] = lens
        # result lengths = elementwise sum of input lengths
        total = helper.create_variable_for_type_inference("int32")
        helper.append_op(type="sum", inputs={"X": lens},
                         outputs={"Out": [total]})
        out._seq_lengths = total
    helper.append_op(
        type="sequence_concat", inputs=inputs, outputs={"Out": [out]},
    )
    return out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", dtype="float32"):
    """One GRU cell step (reference gru_unit_op.cc): input [N, 3H] (the
    x-projection), hidden [N, H] -> new hidden [N, H]. Returns
    (hidden, reset_hidden_prev, gate)."""
    acts = {"identity": 0, "sigmoid": 1, "tanh": 2, "relu": 3}
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    H = size // 3
    weight = helper.create_parameter(helper.param_attr, shape=[H, 3 * H],
                                     dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr, shape=[1, 3 * H],
                                   dtype=dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    gate = helper.create_variable_for_type_inference(dtype)
    reset = helper.create_variable_for_type_inference(dtype)
    if hidden.shape is not None:
        out.desc.shape = list(hidden.shape)
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden], "Weight": [weight],
                "Bias": [bias]},
        outputs={"Hidden": [out], "Gate": [gate], "ResetHiddenPrev": [reset]},
        attrs={"activation": acts[activation],
               "gate_activation": acts[gate_activation]},
    )
    return out, reset, gate


def sequence_mask(x, maxlen=None, maxlen_ref=None, dtype="int64"):
    """mask[i, t] = t < x[i] (reference-era sequence padding mask). Provide
    `maxlen` (static) or `maxlen_ref` (a padded [N, T, ...] var whose traced
    time extent supplies it)."""
    helper = LayerHelper("sequence_mask")
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x]}
    if maxlen_ref is not None:
        inputs["MaxLenRef"] = [maxlen_ref]
    helper.append_op(
        type="sequence_mask", inputs=inputs, outputs={"Y": [out]},
        attrs={"maxlen": -1 if maxlen is None else int(maxlen),
               "out_dtype": dtype},
    )
    return out


def batch_gather(x, index):
    """x [B, K, ...], index [B, K'] -> [B, K', ...]: per-batch gather on
    axis 1 (beam-search parent selection)."""
    helper = LayerHelper("batch_gather")
    out = helper.create_variable_for_type_inference(x.dtype)
    if x.shape is not None and index.shape is not None:
        out.desc.shape = list(index.shape[:2]) + list(x.shape[2:])
    helper.append_op(
        type="batch_gather", inputs={"X": [x], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, ids=None,
                level=0):
    """One beam expansion step over fixed [B, beam] state (reference
    beam_search_op.cc keeps beams as LoD levels and shrinks finished ones;
    here finished beams are frozen — see ops/beam_search_ops.py). `scores`
    are this step's log-probs [B, beam, V]."""
    helper = LayerHelper("beam_search")
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference(scores.dtype)
    parent = helper.create_variable_for_type_inference("int32")
    inputs = {"PreIds": [pre_ids], "PreScores": [pre_scores],
              "Scores": [scores]}
    if ids is not None:
        inputs["Ids"] = [ids]
    helper.append_op(
        type="beam_search", inputs=inputs,
        outputs={"SelectedIds": [sel_ids], "SelectedScores": [sel_scores],
                 "ParentIdx": [parent]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": int(level)},
    )
    return sel_ids, sel_scores, parent


def linear_chain_crf(input, label, param_attr=None):
    """Linear-chain CRF NLL (reference layers/nn.py linear_chain_crf, op
    linear_chain_crf_op.cc). `input` is the padded emission [N, T, D] with
    lengths companion; `label` [N, T] (+lengths). The transition parameter
    is [D+2, D]: rows 0/1 are start/end weights, rows 2: the tag-to-tag
    matrix. Returns per-sequence NLL [N, 1]."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, [size + 2, size], input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    e_exps = helper.create_variable_for_type_inference(input.dtype)
    t_exps = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": [input], "Transition": [transition],
              "Label": [label]}
    lengths = seq_lengths_of(input) or seq_lengths_of(label)
    if lengths is not None:
        inputs["Lengths"] = [lengths]
    helper.append_op(
        type="linear_chain_crf", inputs=inputs,
        outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                 "EmissionExps": [e_exps], "TransitionExps": [t_exps]},
    )
    return ll


def crf_decoding(input, param_attr, label=None):
    """Viterbi decode against the CRF transition parameter created by
    linear_chain_crf (reference layers/nn.py crf_decoding). With `label`,
    returns per-position agreement 0/1 instead of the path (reference
    crf_decoding_op.cc semantics)."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    # the transition parameter already exists (created by linear_chain_crf
    # under the shared ParamAttr name, e.g. 'crfw') — look it up, don't re-init
    transition = helper.main_program.global_block().var(helper.param_attr.name)
    path = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    lengths = seq_lengths_of(input) or (
        seq_lengths_of(label) if label is not None else None)
    if lengths is not None:
        inputs["Lengths"] = [lengths]
    helper.append_op(
        type="crf_decoding", inputs=inputs,
        outputs={"ViterbiPath": [path]},
    )
    _propagate_lengths(input, path)
    return path


def beam_search_decode(ids, scores, parents, beam_size=None, end_id=0):
    """Backtrack stacked per-step beam selections ([T, B, beam] each) into
    sentences [B, beam, T] + final scores [B, beam] (reference
    beam_search_decode_op.cc)."""
    helper = LayerHelper("beam_search_decode")
    sent_ids = helper.create_variable_for_type_inference("int64")
    sent_scores = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores], "Parents": [parents]},
        outputs={"SentenceIds": [sent_ids], "SentenceScores": [sent_scores]},
        attrs={"end_id": int(end_id)},
    )
    return sent_ids, sent_scores


def lod_reset(x, y=None, target_lod=None):
    """Repartition x's token stream under new sequence boundaries
    (reference layers lod_reset -> lod_reset_op.cc): boundaries come from
    `y`'s lengths or the static offset vector `target_lod`. Returns the
    re-padded tensor; its lengths companion is `<out>@LEN`."""
    if y is None and target_lod is None:
        raise ValueError("lod_reset requires y or target_lod")
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype)
    out_lens = helper.main_program.current_block().create_var(
        name=out.name + LEN_SUFFIX, shape=[-1], dtype="int32",
        stop_gradient=True, persistable=False,
    )
    inputs = {"X": [x]}
    x_lens = seq_lengths_of(x)
    if x_lens is not None:
        inputs["XLengths"] = [x_lens]
    attrs = {}
    if y is not None:
        inputs["Y"] = [y]
        y_lens = seq_lengths_of(y)
        if y_lens is None:
            raise ValueError(f"'{y.name}' has no lengths companion")
        inputs["YLengths"] = [y_lens]
    else:
        attrs["target_lod"] = [int(v) for v in target_lod]
    helper.append_op(
        type="lod_reset", inputs=inputs,
        outputs={"Out": [out], "OutLengths": [out_lens]}, attrs=attrs,
    )
    out._seq_lengths = out_lens
    return out


def dynamic_lstmp(input, size, proj_size, use_peepholes=True,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", param_attr=None, bias_attr=None,
                  dtype="float32", name=None):
    """LSTM with recurrent projection (reference layers/nn.py:423
    dynamic_lstmp -> lstmp_op): input carries the x-projection
    [N, T, 4*size]; the recurrent state fed back is proj(h_t) of width
    proj_size. Returns (projection, cell)."""
    helper = LayerHelper("lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    size = size // 4
    weight = helper.create_parameter(
        helper.param_attr, shape=[proj_size, 4 * size], dtype=dtype)
    proj_weight = helper.create_parameter(
        helper.param_attr, shape=[size, proj_size], dtype=dtype)
    inputs_bias = {}
    if helper.bias_attr is not False:  # bias_attr=False opts out
        bias_size = 7 * size if use_peepholes else 4 * size
        inputs_bias["Bias"] = [helper.create_parameter(
            helper.bias_attr, shape=[1, bias_size], dtype=dtype,
            is_bias=True)]
    projection = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    extras = [helper.create_variable_for_type_inference(dtype)
              for _ in range(5)]
    inputs = {"Input": [input], "Weight": [weight],
              "ProjWeight": [proj_weight], **inputs_bias}
    lens = seq_lengths_of(input)
    if lens is not None:
        inputs["Lengths"] = [lens]
    helper.append_op(
        type="lstmp", inputs=inputs,
        outputs={"Projection": [projection], "Cell": [cell],
                 "BatchedProjection": [extras[0]],
                 "BatchedCell": [extras[1]], "BatchedInput": [extras[2]],
                 "BatchedHidden": [extras[3]], "OrderedP0": [extras[4]]},
        attrs={
            "use_peepholes": use_peepholes, "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
            "proj_activation": proj_activation,
        },
    )
    _propagate_lengths(input, projection)
    _propagate_lengths(input, cell)
    return projection, cell


def ctc_greedy_decoder(input, blank, name=None):
    """Greedy CTC decoding (reference layers/nn.py ctc_greedy_decoder ->
    ctc_align): argmax per step, merge repeats, drop blanks. Returns the
    left-packed token tensor ([N, T], -1 padded — the dense equivalent of
    the reference's variable-length LoD output)."""
    from .tensor import argmax

    helper = LayerHelper("ctc_greedy_decoder", name=name)
    ids = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int32")
    inputs = {"Input": [ids]}
    lens = seq_lengths_of(input)
    if lens is not None:
        inputs["InputLength"] = [lens]
    helper.append_op(
        type="ctc_align", inputs=inputs,
        outputs={"Output": [out], "OutputLength": [out_len]},
        attrs={"blank": int(blank), "merge_repeated": True},
    )
    # ctc_align emits [N, 1] (reference padding-mode shape); the repo's
    # lengths convention is flat [N] — reshape before attaching
    flat_len = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="reshape", inputs={"X": [out_len]},
        outputs={"Out": [flat_len]}, attrs={"shape": [-1]},
    )
    out._seq_lengths = flat_len
    return out
