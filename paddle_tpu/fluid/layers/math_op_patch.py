"""Monkey-patch arithmetic dunders onto Variable (reference
python/paddle/fluid/layers/math_op_patch.py) — `a + b` emits elementwise ops."""
from __future__ import annotations

from ..framework import Variable
from ..layer_helper import LayerHelper


def _binary(op_type, reverse=False):
    def impl(self, other):
        helper = LayerHelper(op_type)
        if not isinstance(other, Variable):
            from .tensor import fill_constant

            if isinstance(other, (int, float)):
                # scalar fast-path via scale op where possible
                other = fill_constant(
                    shape=[1], dtype=self.dtype, value=float(other)
                )
            else:
                raise TypeError(f"unsupported operand for {op_type}: {type(other)}")
        x, y = (other, self) if reverse else (self, other)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
            attrs={"axis": -1},
        )
        return out

    return impl


def _scale(scale_val=None, bias_val=None):
    def impl(self):
        helper = LayerHelper("scale")
        out = helper.create_variable_for_type_inference(dtype=self.dtype)
        helper.append_op(
            type="scale", inputs={"X": [self]}, outputs={"Out": [out]},
            attrs={"scale": scale_val if scale_val is not None else 1.0,
                   "bias": bias_val if bias_val is not None else 0.0},
        )
        return out

    return impl


Variable.__add__ = _binary("elementwise_add")
Variable.__radd__ = _binary("elementwise_add", reverse=True)
Variable.__sub__ = _binary("elementwise_sub")
Variable.__rsub__ = _binary("elementwise_sub", reverse=True)
Variable.__mul__ = _binary("elementwise_mul")
Variable.__rmul__ = _binary("elementwise_mul", reverse=True)
Variable.__truediv__ = _binary("elementwise_div")
Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
Variable.__pow__ = _binary("elementwise_pow")
Variable.__rpow__ = _binary("elementwise_pow", reverse=True)
Variable.__neg__ = _scale(scale_val=-1.0)
Variable.__lt__ = _binary("less_than")
Variable.__le__ = _binary("less_equal")
Variable.__gt__ = _binary("greater_than")
Variable.__ge__ = _binary("greater_equal")
