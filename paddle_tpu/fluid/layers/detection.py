"""SSD detection layer builders (reference python/paddle/fluid/layers/
detection.py: prior_box, multi_box_head, bipartite_match, target_assign,
box_coder, detection_output, ssd_loss, detection_map).

Dense-tensor redesign: ground truth arrives as fixed-width padded tensors
[N, G, ...] instead of LoD, so the whole SSD loss is one XLA computation.
"""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper
from . import nn, tensor

__all__ = [
    "prior_box", "multi_box_head", "bipartite_match", "target_assign",
    "box_coder", "detection_output", "ssd_loss", "detection_map",
    "iou_similarity", "multiclass_nms", "mine_hard_examples",
]


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(np.atleast_1d(min_sizes).astype(float)),
            "max_sizes": list(np.atleast_1d(max_sizes).astype(float))
            if max_sizes else [],
            "aspect_ratios": list(
                np.atleast_1d(aspect_ratios if aspect_ratios else [1.0])
                .astype(float)),
            "variances": list(
                np.atleast_1d(variance if variance else [0.1, 0.1, 0.2, 0.2])
                .astype(float)),
            "flip": flip, "clip": clip,
            "step_w": float(steps[0]), "step_h": float(steps[1]),
            "offset": offset,
        },
    )
    return boxes, variances


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    helper.append_op(
        type="box_coder",
        inputs={"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                "TargetBox": [target_box]},
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = helper.create_variable_for_type_inference("int32")
    match_dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match_indices],
                 "ColToRowMatchDist": [match_dist]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(
        type="target_assign", inputs=inputs,
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": mismatch_value},
    )
    return out, out_weight


def mine_hard_examples(cls_loss, match_indices, match_dist=None, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5, name=None):
    helper = LayerHelper("mine_hard_examples", name=name)
    neg_indices = helper.create_variable_for_type_inference("int32")
    updated = helper.create_variable_for_type_inference("int32")
    inputs = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss]
    if match_dist is not None:
        inputs["MatchDist"] = [match_dist]
    helper.append_op(
        type="mine_hard_examples", inputs=inputs,
        outputs={"NegIndices": [neg_indices],
                 "UpdatedMatchIndices": [updated]},
        attrs={"neg_pos_ratio": neg_pos_ratio,
               "neg_dist_threshold": neg_dist_threshold},
    )
    return neg_indices, updated


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, background_label=0,
                   nms_eta=1.0, name=None):
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "background_label": background_label, "nms_eta": nms_eta},
    )
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode predicted offsets against priors then NMS
    (reference detection.py detection_output)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores = nn.softmax(scores)
    scores = nn.transpose(scores, perm=[0, 2, 1])  # [N, C, P]
    return multiclass_nms(decoded, scores, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label, nms_eta=nms_eta)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1):
    """Per-feature-map loc/conf conv heads + priors, concatenated
    (reference detection.py multi_box_head)."""
    n_layer = len(inputs)
    if min_sizes is None:
        # evenly spaced ratios between min_ratio and max_ratio (percent)
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_layer - 2)) if n_layer > 2 else 0
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n_layer - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n_layer - 1]

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        Ms = (max_sizes[i] if isinstance(max_sizes[i], (list, tuple))
              else [max_sizes[i]]) if max_sizes else []
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        step_lay = steps[i] if steps else [0.0, 0.0]
        if not isinstance(step_lay, (list, tuple)):
            step_lay = [step_lay, step_lay]
        box, var = prior_box(feat, image, ms, Ms, ar, flip=flip, clip=clip,
                             steps=step_lay, offset=offset)
        # num priors from static shape [H, W, np, 4]
        num_priors = box.shape[2]
        n_loc = num_priors * 4
        loc = nn.conv2d(feat, num_filters=n_loc, filter_size=kernel_size,
                        padding=pad, stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = nn.reshape(loc, shape=[0, -1, 4])
        n_conf = num_priors * num_classes
        conf = nn.conv2d(feat, num_filters=n_conf, filter_size=kernel_size,
                         padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = nn.reshape(conf, shape=[0, -1, num_classes])
        box = nn.reshape(box, shape=[-1, 4])
        var = nn.reshape(var, shape=[-1, 4])
        locs.append(loc)
        confs.append(conf)
        boxes_l.append(box)
        vars_l.append(var)

    mbox_locs = tensor.concat(locs, axis=1)
    mbox_confs = tensor.concat(confs, axis=1)
    boxes = tensor.concat(boxes_l, axis=0)
    variances = tensor.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss = smooth-L1 loc loss on matched priors +
    softmax conf loss on matched + hard-negative priors
    (reference detection.py ssd_loss). Dense gt: gt_box [N, G, 4],
    gt_label [N, G] (−1 pad)."""
    helper = LayerHelper("ssd_loss")
    dtype = location.dtype
    # static prior count from the prior tensor [P, 4] (downstream op outputs
    # have no inferred shape, so reshape targets are built from it)
    num_priors = int(prior_box.shape[0])

    # 1. match priors to gt per image: iou [N, G, P]
    iou = iou_similarity(gt_box, prior_box)
    matched_indices, matched_dist = bipartite_match(
        iou, match_type, overlap_threshold)

    # 2. conf loss per prior (vs background) for mining
    num_classes = confidence.shape[-1]
    # gather gt labels for matched priors
    gathered_label, label_weight = target_assign(
        _gt_label_3d(gt_label), matched_indices,
        mismatch_value=background_label)
    conf_for_loss = nn.reshape(confidence, shape=[-1, num_classes])
    target_label_flat = nn.reshape(gathered_label, shape=[-1, 1])
    conf_loss = nn.softmax_with_cross_entropy(conf_for_loss,
                                              target_label_flat)
    conf_loss = nn.reshape(conf_loss, shape=[-1, num_priors])

    # 3. hard-negative mining
    neg_indices, updated_indices = mine_hard_examples(
        conf_loss, matched_indices, match_dist=matched_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap)

    # 4. localization targets for matched priors, encoded center-size against
    # each prior — the loc head therefore learns the same code that
    # detection_output's decode_center_size expects at inference
    loc_target, loc_weight = target_assign(
        gt_box, matched_indices, mismatch_value=0)
    if prior_box_var is not None:
        loc_target = box_coder(prior_box, prior_box_var, loc_target,
                               code_type="encode_center_size")
    # per-prior smooth-L1 via the elementwise huber op (smooth_l1_loss sums
    # to [N, 1]; here mining needs a [N, P] map)
    hub = helper.create_variable_for_type_inference(dtype)
    resid = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="huber_loss", inputs={"X": [location], "Y": [loc_target]},
        outputs={"Out": [hub], "Residual": [resid]}, attrs={"delta": 1.0})
    loc_loss = nn.reduce_sum(hub, dim=-1)
    loc_loss = nn.elementwise_mul(
        loc_loss, nn.reshape(loc_weight, shape=[-1, num_priors]))

    # 5. conf loss over matched + mined negatives
    _, conf_weight = target_assign(_gt_label_3d(gt_label), updated_indices,
                                   negative_indices=neg_indices,
                                   mismatch_value=background_label)
    conf_loss = nn.elementwise_mul(
        conf_loss, nn.reshape(conf_weight, shape=[-1, num_priors]))

    loss = nn.elementwise_add(
        nn.scale(nn.reduce_sum(loc_loss, dim=-1), scale=loc_loss_weight),
        nn.scale(nn.reduce_sum(conf_loss, dim=-1), scale=conf_loss_weight))
    if normalize:
        # normalize by number of matched (positive) priors
        pos = tensor.cast(
            nn.reshape(label_weight, shape=[-1, num_priors]), "float32")
        denom = nn.reduce_sum(pos, dim=-1)
        denom = nn.elementwise_max(
            denom, tensor.fill_constant(shape=[1], dtype="float32", value=1.0))
        loss = nn.elementwise_div(loss, denom)
    return nn.reshape(loss, shape=[-1, 1])


def _gt_label_3d(gt_label):
    """[N, G] int labels -> [N, G, 1] for target_assign gather."""
    return nn.reshape(gt_label, shape=[gt_label.shape[0],
                                       gt_label.shape[1], 1])


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral"):
    helper = LayerHelper("detection_map")
    map_out = helper.create_variable_for_type_inference("float32")
    accum_pos = helper.create_variable_for_type_inference("int32")
    accum_tp = helper.create_variable_for_type_inference("float32")
    accum_fp = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="detection_map",
        inputs={"DetectRes": [detect_res], "Label": [label]},
        outputs={"MAP": [map_out], "AccumPosCount": [accum_pos],
                 "AccumTruePos": [accum_tp], "AccumFalsePos": [accum_fp]},
        attrs={"overlap_threshold": overlap_threshold,
               "class_num": class_num,
               "background_label": background_label,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version},
    )
    return map_out
