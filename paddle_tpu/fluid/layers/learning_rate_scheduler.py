"""Learning-rate decay schedules built as graph ops over a global step
counter (reference python/paddle/fluid/layers/learning_rate_scheduler.py —
exponential_decay:36, natural_exp_decay:73, inverse_time_decay:105,
polynomial_decay:142, piecewise_decay:192; step counter from
`autoincreased_step_counter`, nn.py:3323).

On TPU the schedule is part of the compiled step function: the counter is a
persistable scalar bumped in-graph each step, so the whole decay computation
fuses into the training XLA computation instead of a host-side callback.
"""
from __future__ import annotations

from . import control_flow
from . import nn
from . import ops
from . import tensor

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
]


def _decay_step_counter(begin=0):
    # float32 global step; the first observed value is `begin` (the counter
    # increments after the decay math reads it)
    global_step = nn.autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1
    )
    return tensor.cast(global_step, "float32")


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr * decay_rate ^ (global_step / decay_steps)"""
    global_step = _decay_step_counter()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate * (decay_rate ** div_res)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr * exp(-decay_rate * (global_step / decay_steps))"""
    global_step = _decay_step_counter()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate * ops.exp(-1.0 * decay_rate * div_res)


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr / (1 + decay_rate * (global_step / decay_steps))"""
    global_step = _decay_step_counter()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate / (1.0 + decay_rate * div_res)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    """(lr - end_lr) * (1 - min(step, decay_steps)/decay_steps)^power + end_lr"""
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(global_step / float(decay_steps))
        zero_var = tensor.fill_constant(shape=[1], dtype="float32", value=0.0)
        one_var = tensor.fill_constant(shape=[1], dtype="float32", value=1.0)
        with control_flow.Switch() as switch:
            with switch.case(control_flow.equal(global_step, zero_var)):
                tensor.assign(one_var, output=div_res)
        decay_steps_var = tensor.fill_constant(
            shape=[1], dtype="float32", value=float(decay_steps)
        )
        decay_steps_f = decay_steps_var * div_res
    else:
        decay_steps_f = tensor.fill_constant(
            shape=[1], dtype="float32", value=float(decay_steps)
        )
        global_step = nn.elementwise_min(x=global_step, y=decay_steps_f)

    frac = (1.0 - global_step / decay_steps_f) ** power
    return (learning_rate - end_learning_rate) * frac + end_learning_rate


def piecewise_decay(boundaries, values):
    """Piecewise-constant schedule: values[i] while step < boundaries[i]."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    global_step = _decay_step_counter()
    lr = tensor.create_global_var(
        shape=[1], value=0.0, dtype="float32", persistable=True,
        name="learning_rate",
    )
    with control_flow.Switch() as switch:
        for i in range(len(boundaries)):
            boundary_val = tensor.fill_constant(
                shape=[1], dtype="float32", value=float(boundaries[i])
            )
            value_var = tensor.fill_constant(
                shape=[1], dtype="float32", value=float(values[i])
            )
            with switch.case(control_flow.less_than(global_step, boundary_val)):
                tensor.assign(value_var, output=lr)
        last_value_var = tensor.fill_constant(
            shape=[1], dtype="float32", value=float(values[-1])
        )
        with switch.default():
            tensor.assign(last_value_var, output=lr)
    return lr


def noam_decay(d_model, warmup_steps):
    """Transformer LR: d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (post-dates the reference's scheduler set; standard for the Transformer
    NMT config the reference benchmarks)."""
    global_step = _decay_step_counter(1)
    a = global_step ** -0.5
    b = (warmup_steps ** -1.5) * global_step
    return (d_model ** -0.5) * nn.elementwise_min(x=a, y=b)
