"""Tensor layers (reference python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast", "concat",
    "sums", "assign", "fill_constant", "fill_constant_batch_size_like",
    "ones", "zeros", "argmin", "argmax",
    "save", "save_combine", "load", "load_combine",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(
        name=helper.name if name else None, dtype=dtype, persistable=persistable
    )


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr.to_attr(attr)
    if attr.name is None and name is not None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    from ..initializer import ConstantInitializer

    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name
    )
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    from ..core import convert_dtype

    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"in_dtype": x.dtype, "out_dtype": convert_dtype(dtype)},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(
        type="concat", inputs={"X": input}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    # elementwise over the time axis: sequence lengths survive
    from .sequence import _propagate_lengths

    for x in input:
        _propagate_lengths(x, out)
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if output is None:
        output = helper.create_variable_for_type_inference(
            dtype=input.dtype if isinstance(input, Variable) else "float32"
        )
    if isinstance(input, Variable):
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
    else:
        arr = np.asarray(input)
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "values": arr.ravel().tolist(),
            },
        )
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": out.dtype, "value": float(value)},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value, input_dim_idx=0,
                                  output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape), "dtype": out.dtype, "value": float(value),
            "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def argmin(x, axis=0):
    return _arg_min_max("arg_min", x, axis)


def argmax(x, axis=0):
    return _arg_min_max("arg_max", x, axis)


def _arg_min_max(op_type, x, axis):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def save(x, file_path, overwrite=True):
    """Emit an in-graph save op for one var (reference layers/tensor.py
    save -> save_op.cc; runs host-side at the program's edge)."""
    helper = LayerHelper("save")
    helper.main_program.current_block().append_op(
        "save", inputs={"X": [x]}, outputs={},
        attrs={"file_path": str(file_path), "overwrite": bool(overwrite)},
    )


def save_combine(x, file_path, overwrite=True):
    """Save several vars into one file (reference save_combine_op.cc)."""
    helper = LayerHelper("save_combine")
    helper.main_program.current_block().append_op(
        "save_combine", inputs={"X": list(x)}, outputs={},
        attrs={"file_path": str(file_path), "overwrite": bool(overwrite)},
    )


def load(out, file_path):
    """Emit an in-graph load op into `out` (reference load_op.cc)."""
    helper = LayerHelper("load")
    helper.main_program.current_block().append_op(
        "load", inputs={}, outputs={"Out": [out]},
        attrs={"file_path": str(file_path)},
    )


def load_combine(out, file_path):
    """Load several vars from one file (reference load_combine_op.cc)."""
    helper = LayerHelper("load_combine")
    helper.main_program.current_block().append_op(
        "load_combine", inputs={}, outputs={"Out": list(out)},
        attrs={"file_path": str(file_path)},
    )
