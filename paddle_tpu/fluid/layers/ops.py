"""Auto-generated thin wrappers over registered ops (reference
python/paddle/fluid/layers/ops.py + layer_function_generator.py:222)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__act_ops__ = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "abs", "ceil", "floor", "round", "reciprocal", "log", "square",
    "softplus", "softsign", "brelu", "leaky_relu", "soft_relu", "elu", "relu6",
    "pow", "stanh", "hard_sigmoid", "swish", "thresholded_relu", "hard_shrink",
    "gelu", "cumsum", "sign", "log_softmax",
]

__all__ = list(__act_ops__)


def _make_unary(op_type):
    def layer(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type, inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs
        )
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"emits the `{op_type}` op (see ops/activations.py)"
    return layer


for _op in __act_ops__:
    globals()[_op] = _make_unary(_op)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="uniform_random", outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": out.dtype, "min": min, "max": max,
               "seed": seed},
    )
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="gaussian_random", outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": out.dtype, "mean": mean, "std": std,
               "seed": seed},
    )
    return out


__all__ += ["uniform_random", "gaussian_random"]
