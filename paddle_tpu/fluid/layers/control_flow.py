"""Graph-level control flow builders (reference
python/paddle/fluid/layers/control_flow.py: StaticRNN:383, While:608,
ConditionalBlock:1106, Switch:1163, array_write:889, array_read:1017,
less_than:953, increment).

TPU lowering: While -> lax.while_loop (forward-only), ConditionalBlock ->
lax.cond, StaticRNN -> a `recurrent` op unrolled at trace time
(differentiable). See ops/control_flow.py.
"""
from __future__ import annotations

import contextlib

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "While", "StaticRNN", "DynamicRNN", "IfElse", "ConditionalBlock",
    "Switch", "ParallelDo", "Recompute", "get_places", "increment",
    "array_write", "array_read", "array_length", "create_array",
    "less_than", "equal", "zeros_like_array", "Print", "lod_rank_table",
    "reorder_lod_tensor_by_rank", "max_sequence_len",
]


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"step": float(value)},
    )
    return out


def less_than(x, y, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(
        type="less_than", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]},
    )
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(
        type="equal", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]},
    )
    return cond


def create_array(dtype, size, item_shape):
    """Preallocated tensor array [size, *item_shape] (XLA needs static
    extents; the reference's LoDTensorArray grows dynamically)."""
    from .tensor import fill_constant

    return fill_constant(shape=[size] + list(item_shape), dtype=dtype, value=0.0)


def array_write(x, i, array):
    """Writes x at position i. As in the reference (the op's output IS the
    array variable), the write is in-place on `array`'s name — which is also
    what lets an enclosing While carry the array across iterations."""
    helper = LayerHelper("array_write")
    helper.append_op(
        type="write_to_array",
        inputs={"Array": [array], "X": [x], "I": [i]},
        outputs={"Out": [array]},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        type="read_from_array", inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="array_length", inputs={"X": [array]}, outputs={"Out": [out]},
    )
    return out


def lod_rank_table(x, level=0):
    """Index permutation sorting the batch by descending sequence length
    (reference layers/control_flow.py lod_rank_table; the LoDRankTable's
    role on the padded stack — see ops/sequence_ops.py)."""
    from .sequence import seq_lengths_of

    if level != 0:
        raise ValueError(
            "the padded stack has a single ragged level; lod_rank_table "
            f"(level={level}) has no nested-LoD equivalent")
    lens = seq_lengths_of(x)
    if lens is None:
        raise ValueError(
            "lod_rank_table needs a sequence input (padded var with a "
            "lengths companion, e.g. from layers.data(lod_level=1))")
    helper = LayerHelper("lod_rank_table")
    out = helper.create_variable_for_type_inference("int32")
    out.stop_gradient = True
    helper.append_op(
        type="lod_rank_table", inputs={"X": [x], "Lengths": [lens]},
        outputs={"Out": [out]},
    )
    return out


def max_sequence_len(x):
    """Max sequence length in the batch as an int64 [1] tensor (reference
    layers/control_flow.py max_sequence_len — there it reads the
    LoDRankTable; here the lengths companion of the sequence var)."""
    from .sequence import seq_lengths_of

    lens = seq_lengths_of(x)
    if lens is None:
        raise ValueError("max_sequence_len needs a sequence input "
                         "(padded var with a lengths companion)")
    helper = LayerHelper("max_sequence_len")
    # int32: x64 is disabled throughout, so an int64 decl would never match
    # the runtime dtype (and jnp warns on every trace)
    out = helper.create_variable_for_type_inference("int32")
    out.stop_gradient = True
    helper.append_op(
        type="max_sequence_len", inputs={"Lengths": [lens]},
        outputs={"Out": [out]},
    )
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Gather the batch rows into rank order; the lengths companion is
    reordered alongside (reference reorder_lod_tensor_by_rank_op.cc)."""
    from .sequence import seq_lengths_of

    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    lens = seq_lengths_of(x)
    if lens is not None:
        new_lens = helper.create_variable_for_type_inference(lens.dtype)
        new_lens.stop_gradient = True
        helper.append_op(
            type="reorder_lod_tensor_by_rank",
            inputs={"X": [lens], "RankTable": [rank_table]},
            outputs={"Out": [new_lens]},
        )
        out._seq_lengths = new_lens
    return out


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Tensor tap (reference layers/control_flow.py Print, print_op.cc):
    returns `input` unchanged and prints stats + first `summarize` values
    whenever the op executes. `print_phase`: 'forward', 'backward', 'both'
    — backward taps the gradient flowing through. (`first_n` and the
    print_tensor_* switches are accepted for API parity; the XLA-side
    printer always shows name/shape/dtype and prints every step.)"""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={
            "first_n": int(first_n),
            "message": message or input.name,
            "summarize": int(summarize),
            "print_phase": print_phase,
        },
    )
    from .sequence import _propagate_lengths

    _propagate_lengths(input, out)
    return out


def zeros_like_array(x):
    helper = LayerHelper("zeros_like")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]},
    )
    return out


def _scan_block_io(sub, parent_block):
    """Outer vars a finished sub-block touches: returns (touched, written) —
    `touched` = sorted outer-var names the block reads OR writes (write-only
    outer vars still need their pre-loop value as carry init), `written` =
    output names in first-write order."""
    read, written = set(), []
    for op in sub.ops:
        read.update(n for n in op.desc.input_names() if n)
        for n in op.desc.output_names():
            if n and n not in written:
                written.append(n)
    touched = sorted(
        n for n in (read | set(written))
        if n not in sub.vars and parent_block._var_recursive(n) is not None
    )
    return touched, written


def _outer_reads(sub, parent_block, exclude=()):
    """Outer-var names a finished sub-block READS (params + captured
    tensors), minus `exclude` (block-local placeholders like step inputs).
    Shared by DynamicRNN and Pipeline region capture."""
    read = set()
    for op in sub.ops:
        read.update(n for n in op.desc.input_names() if n)
    return sorted(
        n for n in read
        if n not in exclude
        and n not in sub.vars
        and parent_block._var_recursive(n) is not None
    )


class While:
    """reference control_flow.py:608. Usage:
        cond = layers.less_than(i, n)
        w = While(cond)                    # dynamic trip count
        w = While(cond, max_steps=K)       # known trip bound
        with w.block():
            ...ops...  (must update `cond` for termination)

    Both forms support append_backward (the reference's while grad,
    while_op.cc:96). With `max_steps` the loop lowers to a K-step scan with
    freeze-after-exit masking — direct reverse-mode, O(K) memory. Without
    it the gradient is a recompute-based reverse replay of the
    lax.while_loop: O(1) extra memory but O(T^2) recompute, so prefer
    max_steps when a bound is known."""

    def __init__(self, cond, name=None, max_steps=None,
                 grad_segment_len=None, grad_max_segments=None):
        """`grad_segment_len` (S) / `grad_max_segments` (C) tune the
        unbounded-While gradient's segment-checkpointed replay (defaults
        S=32, C=128): backward costs ~3T step evaluations for trip counts
        up to S*C, with S + C carry copies of extra memory."""
        self.helper = LayerHelper("while", name=name)
        if cond.dtype != "bool":
            raise TypeError("condition should be a bool variable")
        self.cond_var = cond
        self.max_steps = int(max_steps) if max_steps else 0
        self.grad_segment_len = int(grad_segment_len or 0)
        self.grad_max_segments = int(grad_max_segments or 0)

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        parent_block = main.current_block()
        sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
            # X = outer vars the block touches; Out = written vars with a
            # pre-loop value (the emitter's loop carry)
            touched, written = _scan_block_io(sub, parent_block)
            carried = [n for n in written
                       if n in touched or n == self.cond_var.name]
            parent_block.append_op(
                type="while",
                inputs={"Condition": [self.cond_var], "X": touched},
                outputs={"Out": carried},
                attrs={
                    "sub_block": sub.idx,
                    "x_var_names": touched,
                    "cond_var_name": self.cond_var.name,
                    "out_var_names": carried,
                    "max_steps": self.max_steps,
                    "grad_segment_len": self.grad_segment_len,
                    "grad_max_segments": self.grad_max_segments,
                },
            )


class ConditionalBlock:
    """reference control_flow.py:1106."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        self.helper = LayerHelper("conditional_block", name=name)
        assert len(inputs) == 1, "one condition var"
        self.cond_var = inputs[0]

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        parent_block = main.current_block()
        sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
            touched, written = _scan_block_io(sub, parent_block)
            carried = [n for n in written if n in touched]
            parent_block.append_op(
                type="conditional_block",
                inputs={"Condition": [self.cond_var], "X": touched},
                outputs={"Out": carried},
                attrs={
                    "sub_block": sub.idx,
                    "x_var_names": touched,
                    "out_var_names": carried,
                },
            )


class Recompute:
    """Gradient rematerialization region (TPU-native capability; the
    2018 reference has no equivalent — its memory story is
    memory_optimization_transpiler reuse). Ops built inside `block()`
    lower under `jax.checkpoint`: their activations are NOT stored for
    backward; the backward pass re-runs the region instead, trading
    FLOPs for HBM — the standard big-model training lever on TPU.

        rc = layers.Recompute()
        with rc.block():
            h = layers.fc(x, size=4096, act="relu")
            h = layers.fc(h, size=4096, act="relu")
        h = rc.output(h)

    Gradients are bit-identical to the non-recompute lowering (the
    deterministic per-op RNG makes dropout replay exactly)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("recompute", name=name)
        self._sub = None
        self._parent = None

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        self._parent = main.current_block()
        self._sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()

    def output(self, *out_vars):
        """Completes the region; returns parent-block vars mirroring
        `out_vars` (one var -> one var, several -> tuple). Writes the
        region makes to OUTER vars (assign/increment into a parent var)
        are carried out as additional op outputs so post-region readers
        see the updated values."""
        if self._sub is None:
            raise RuntimeError("Recompute.output() must follow block()")
        if not out_vars:
            raise ValueError("Recompute.output() needs at least one var")
        sub, parent = self._sub, self._parent
        reads = _outer_reads(sub, parent)
        # an unbounded `while` inside the region would be differentiated
        # by the generic vjp straight through lax.while_loop (its custom
        # recompute-replay grad only fires for a top-level while_grad op
        # desc) — reject it here instead of a deep JAX trace error
        for op in sub.ops:
            if op.desc.type == "while" and not op.desc.attrs.get("max_steps"):
                raise ValueError(
                    "Recompute region contains a While without max_steps — "
                    "its gradient cannot lower inside jax.checkpoint; give "
                    "the loop max_steps or move it outside the region")
        produced = {n for op in sub.ops for n in op.desc.output_names() if n}
        for v in out_vars:
            if v.name not in produced and v.name not in reads:
                raise ValueError(
                    f"Recompute.output(): '{v.name}' is neither produced "
                    "nor read by the region — pass a var computed inside "
                    "block()")
        # outer vars the region writes IN PLACE: carried out name-for-name
        outer_written = [
            n for n in produced
            if n not in sub.vars and parent._var_recursive(n) is not None
        ]
        outs = []
        for i, v in enumerate(out_vars):
            outs.append(parent.create_var(
                name=f"{self.helper.name}.out{i}", dtype=v.dtype,
                shape=list(v.shape) if v.shape is not None else None,
            ))
        parent.append_op(
            type="recompute",
            inputs={"X": reads},
            outputs={"Out": outs + [parent._var_recursive(n)
                                    for n in sorted(outer_written)]},
            attrs={
                "sub_block": sub.idx,
                "x_var_names": reads,
                "out_var_names": [v.name for v in out_vars]
                                 + sorted(outer_written),
            },
        )
        return outs[0] if len(outs) == 1 else tuple(outs)


def get_places(device_count=0, device_type=None):
    """reference layers/device.py get_places / operators/get_places_op.cc:
    a PLACE_LIST var naming the devices a ParallelDo spreads over. Here a
    place is a mesh position, so the var is an int32 [n] of device indices
    (0 = all visible devices at run time). `device_type` is accepted for
    API parity and ignored — the mesh decides CPU/TPU."""
    helper = LayerHelper("get_places")
    out = helper.create_variable_for_type_inference("int32")
    out.stop_gradient = True
    helper.append_op(
        type="get_places", inputs={}, outputs={"Out": [out]},
        attrs={"device_count": int(device_count or 0)},
    )
    return out


class ParallelDo:
    """reference layers/control_flow.py:234 + operators/parallel_do_op.cc:115
    — data-parallel region: the reference splits the batch across places,
    re-runs the sub-block per device on threads, and all-reduces grads.

    TPU-native semantics: the captured sub-block is traced ONCE over the
    full batch inside the surrounding jit; sharding the batch axis across
    the mesh (ParallelExecutor / GSPMD) then yields exactly the reference's
    split-run-allreduce — XLA inserts the collectives. The region is
    differentiable through the generic emitter vjp, so grads flow with no
    ParallelDo-specific grad machinery (the reference needed NCCL op
    inserts in backward.py).

    Usage (reference API):
        places = layers.get_places()
        pd = layers.ParallelDo(places)
        with pd.do():
            x_ = pd.read_input(x)
            loss = net(x_)
            pd.write_output(loss)
        loss, = pd()
    """

    def __init__(self, places, use_nccl=False, name=None):
        self.helper = LayerHelper("parallel_do", name=name)
        self.places = places
        self.use_nccl = use_nccl  # parity only: collectives come from GSPMD
        self._inputs = []   # (outer Variable, sub-block placeholder)
        self._outputs = []  # sub-block Variables registered by write_output
        self._sub = None
        self._parent = None
        self._result_vars = None

    def read_input(self, var):
        if self._sub is None:
            raise RuntimeError("read_input() must be called inside do()")
        placeholder = self._sub.create_var(
            name=f"{var.name}@PDO", shape=var.shape, dtype=var.dtype,
            lod_level=getattr(var, "lod_level", 0),
        )
        self._inputs.append((var, placeholder))
        return placeholder

    def write_output(self, var):
        if self._sub is None:
            raise RuntimeError("write_output() must be called inside do()")
        self._outputs.append(var)

    @contextlib.contextmanager
    def do(self):
        main = self.helper.main_program
        self._parent = main.current_block()
        self._sub = main.create_block()
        try:
            yield
        except BaseException:
            # body failed: surface the user's exception untouched; don't
            # append an op over the half-built sub-block
            main.rollback()
            raise
        else:
            main.rollback()
            if not self._outputs:
                raise ValueError("ParallelDo region wrote no outputs — "
                                 "call pd.write_output(var) inside do()")
            sub, parent = self._sub, self._parent
            placeholder_names = [p.name for _, p in self._inputs]
            reads = _outer_reads(sub, parent, exclude=placeholder_names)
            # parent-scope result vars mirror the registered sub-block vars
            # (shapes known from the traced body -> downstream layers keep
            # build-time shape inference)
            self._result_vars = [
                parent.create_var(
                    name=f"{o.name}@PDO_OUT", shape=o.shape, dtype=o.dtype,
                )
                for o in self._outputs
            ]
            parent.append_op(
                type="parallel_do",
                inputs={
                    "Places": [self.places],
                    "Inputs": [v for v, _ in self._inputs],
                    "X": reads,
                },
                outputs={"Out": self._result_vars},
                attrs={
                    "sub_block": sub.idx,
                    "input_var_names": placeholder_names,
                    "x_var_names": reads,
                    "out_var_names": [o.name for o in self._outputs],
                },
            )

    def __call__(self):
        if self._result_vars is None:
            raise RuntimeError("ParallelDo has no results — call pd() after "
                               "a completed `with pd.do():` region")
        outs = self._result_vars
        return outs[0] if len(outs) == 1 else tuple(outs)


class Switch:
    """reference control_flow.py:1163 — chained conditional blocks."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conditions = []

    @contextlib.contextmanager
    def case(self, condition):
        if self.pre_not_conditions:
            helper = LayerHelper("logical_and")
            combined = helper.create_variable_for_type_inference("bool")
            helper.append_op(
                type="logical_and",
                inputs={"X": [self.pre_not_conditions[-1]],
                        "Y": [condition]},
                outputs={"Out": [combined]},
            )
            cond_to_use = combined
        else:
            cond_to_use = condition
        helper = LayerHelper("logical_not")
        not_cond = helper.create_variable_for_type_inference("bool")
        helper.append_op(
            type="logical_not", inputs={"X": [condition]},
            outputs={"Out": [not_cond]},
        )
        if self.pre_not_conditions:
            helper = LayerHelper("logical_and")
            chained = helper.create_variable_for_type_inference("bool")
            helper.append_op(
                type="logical_and",
                inputs={"X": [self.pre_not_conditions[-1]], "Y": [not_cond]},
                outputs={"Out": [chained]},
            )
            not_cond = chained
        self.pre_not_conditions.append(not_cond)
        cb = ConditionalBlock([cond_to_use])
        with cb.block():
            yield

    @contextlib.contextmanager
    def default(self):
        assert self.pre_not_conditions, "default() requires a prior case()"
        cb = ConditionalBlock([self.pre_not_conditions[-1]])
        with cb.block():
            yield

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class IfElse:
    """Per-example branch (reference control_flow.py:1252).

        ie = IfElse(cond)            # cond: bool [N, 1]
        with ie.true_block():
            d = ie.input(x)
            ie.output(layers.fc(d, ...))
        with ie.false_block():
            d = ie.input(x)
            ie.output(layers.scale(d, scale=2.0))
        out, = ie()                  # rows merged by cond

    The reference splits rows into subsets per branch (split_lod_tensor /
    merge_lod_tensor); here both branches compute on the full batch and rows
    are merged with where(cond) — see ops/control_flow.py:ifelse. Both
    branches must output() the same number of (shape-compatible) vars.
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._blocks = {}     # 'true'/'false' -> (sub_block, out_names)
        self._cur = None
        self._outputs = {"true": [], "false": []}

    @contextlib.contextmanager
    def _branch(self, which):
        main = self.helper.main_program
        self._parent = main.current_block()
        sub = main.create_block()
        self._cur = which
        try:
            yield
        finally:
            main.rollback()
            self._blocks[which] = sub
            self._cur = None

    def true_block(self):
        return self._branch("true")

    def false_block(self):
        return self._branch("false")

    def input(self, x):
        """The reference returns the branch's row subset; here the branch
        computes on all rows and the merge masks — so input() is identity."""
        if self._cur is None:
            raise RuntimeError("IfElse.input() must be called inside a block")
        return x

    def output(self, *outs):
        if self._cur is None:
            raise RuntimeError("IfElse.output() must be called inside a block")
        self._outputs[self._cur].extend(outs)

    def __call__(self):
        t, f = self._outputs["true"], self._outputs["false"]
        if "true" not in self._blocks or "false" not in self._blocks:
            raise RuntimeError("IfElse needs both true_block and false_block")
        if len(t) != len(f):
            raise ValueError(
                f"IfElse branches output different counts: {len(t)} vs {len(f)}")
        parent = self._parent
        touched = set()
        for sub in self._blocks.values():
            tr, _ = _scan_block_io(sub, parent)
            touched.update(tr)
        touched.discard(self.cond.name)
        touched = sorted(touched)
        out_vars = [
            parent.create_var(
                name=self.helper.name + f".out{i}", dtype=tv.dtype,
                shape=list(tv.shape) if tv.shape else None,
            )
            for i, tv in enumerate(t)
        ]
        parent.append_op(
            type="ifelse",
            inputs={"Cond": [self.cond], "X": touched},
            outputs={"Out": out_vars},
            attrs={
                "true_block": self._blocks["true"].idx,
                "false_block": self._blocks["false"].idx,
                "x_var_names": touched,
                "cond_var_name": self.cond.name,
                "true_out_names": [v.name for v in t],
                "false_out_names": [v.name for v in f],
            },
        )
        return out_vars


class DynamicRNN:
    """Variable-length RNN over padded sequences (reference
    control_flow.py:1354), trainable.

        drnn = DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x)       # x: [N, T, D] (+@LEN lengths)
            h_prev = drnn.memory(shape=[H], value=0.0)
            ctx_s = drnn.static_input(enc)  # per-example non-sequence input
            h = layers.fc(input=[x_t, h_prev], size=H, act='tanh')
            drnn.update_memory(h_prev, h)
            drnn.output(h)
        out = drnn()                        # [N, T, H], lengths propagated

    The reference shrinks the running batch as short sequences finish
    (lod_rank_table/shrink_rnn_memory); the TPU lowering scans the static
    [N, T] extent with per-example masking (ops/control_flow.py:
    dynamic_recurrent) — memories freeze and outputs are zero past each
    sequence's length, so sequence_last_step() picks the true final state.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._sub = None
        self._parent = None
        self.step_inputs = []    # (full_var, step_var)
        self.static_inputs = []  # (outer_var, step_var)
        self.memories = []       # [pre_var, updated_var|None, init_var]
        self.outputs = []
        self._lengths = None
        self._out_vars = None

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        self._parent = main.current_block()
        self._sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
            self._complete()

    def step_input(self, x):
        from .sequence import seq_lengths_of

        if self._lengths is None:
            self._lengths = seq_lengths_of(x)
        sv = self._sub.create_var(
            name=x.name + "@dstep", dtype=x.dtype,
            shape=[x.shape[0]] + list(x.shape[2:]) if x.shape else None,
        )
        self.step_inputs.append((x, sv))
        return sv

    def static_input(self, x):
        sv = self._sub.create_var(
            name=x.name + "@dstatic", dtype=x.dtype,
            shape=list(x.shape) if x.shape else None,
        )
        self.static_inputs.append((x, sv))
        return sv

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        if init is None:
            if not self.step_inputs:
                raise ValueError(
                    "DynamicRNN.memory(shape=...) needs a prior step_input "
                    "(batch reference)")
            from .tensor import fill_constant_batch_size_like

            # created in the parent block, batch-matched to the sequence input
            main = self.helper.main_program
            main.current_block_idx = self._parent.idx
            try:
                init = fill_constant_batch_size_like(
                    input=self.step_inputs[0][0], shape=[-1] + list(shape),
                    dtype=dtype, value=value)
            finally:
                main.current_block_idx = self._sub.idx
        pre = self._sub.create_var(
            name=init.name + "@dpre_mem", dtype=init.dtype,
            shape=list(init.shape) if init.shape else None,
        )
        self.memories.append([pre, None, init])
        return pre

    def update_memory(self, mem, var):
        for m in self.memories:
            if m[0] is mem:
                m[1] = var
                return
        raise ValueError("update_memory: unknown memory var")

    def output(self, *outputs):
        self.outputs.extend(outputs)

    def _complete(self):
        if not self.step_inputs:
            raise ValueError("DynamicRNN needs at least one step_input")
        assert all(m[1] is not None for m in self.memories), (
            "every memory needs update_memory()")
        step_locals = {sv.name for _, sv in self.step_inputs}
        step_locals.update(sv.name for _, sv in self.static_inputs)
        step_locals.update(m[0].name for m in self.memories)
        params = _outer_reads(self._sub, self._parent, exclude=step_locals)
        self._out_vars = []
        for o in self.outputs:
            ov = self._parent.create_var(
                name=o.name + "@dseq", dtype=o.dtype,
                shape=[o.shape[0], -1] + list(o.shape[1:]) if o.shape else None,
            )
            if self._lengths is not None:
                ov._seq_lengths = self._lengths
            self._out_vars.append(ov)
        inputs = {
            "StepInputs": [x for x, _ in self.step_inputs],
            "MemInit": [m[2] for m in self.memories],
            "StaticInputs": [x for x, _ in self.static_inputs],
            "Params": params,
        }
        if self._lengths is not None:
            inputs["Lengths"] = [self._lengths]
        self._parent.append_op(
            type="dynamic_recurrent",
            inputs=inputs,
            outputs={"Out": self._out_vars},
            attrs={
                "sub_block": self._sub.idx,
                "step_input_vars": [sv.name for _, sv in self.step_inputs],
                "static_input_vars": [sv.name for _, sv in self.static_inputs],
                "memory_links": [[m[0].name, m[1].name] for m in self.memories],
                "step_output_vars": [o.name for o in self.outputs],
                "param_var_names": params,
            },
        )

    def __call__(self):
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


class StaticRNN:
    """reference control_flow.py:383 — define one step; the `recurrent` op
    unrolls it over axis 1 at lowering time (differentiable).

        rnn = StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)          # x: [N, T, D] -> xt: [N, D]
            h_prev = rnn.memory(init=h0)    # h0: [N, H]
            h = layers.fc(input=[xt, h_prev], size=H, act='tanh')
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()                          # [N, T, H]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("recurrent", name=name)
        self._sub = None
        self._parent = None
        self.step_inputs = []   # (full_seq_var, step_var)
        self.memories = []      # (pre_mem_var, mem_var_or_None, init_var)
        self.outputs = []       # step-local output vars

    @contextlib.contextmanager
    def step(self):
        main = self.helper.main_program
        self._parent = main.current_block()
        self._sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
            self._complete()

    def step_input(self, x):
        sv = self._sub.create_var(
            name=x.name + "@step", dtype=x.dtype,
            shape=[x.shape[0]] + list(x.shape[2:]) if x.shape else None,
        )
        self.step_inputs.append((x, sv))
        return sv

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        if init is None:
            raise ValueError("StaticRNN.memory requires an init var "
                             "(create it with layers.fill_constant_batch_size_like)")
        pre = self._sub.create_var(
            name=init.name + "@pre_mem", dtype=init.dtype,
            shape=list(init.shape) if init.shape else None,
        )
        self.memories.append([pre, None, init])
        return pre

    def update_memory(self, mem, var):
        for m in self.memories:
            if m[0] is mem:
                m[1] = var
                return
        raise ValueError("update_memory: unknown memory var")

    def step_output(self, o):
        self.outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        assert all(m[1] is not None for m in self.memories), (
            "every memory needs update_memory()"
        )
        if not self.step_inputs:
            raise ValueError(
                "StaticRNN needs at least one step_input — the trip count "
                "is its time extent (axis 1)"
            )
        # params: outer vars read by step ops (weights/biases), excluding
        # step-local vars — they become explicit op inputs so the generic
        # vjp differentiates through the unrolled steps
        step_locals = {sv.name for _, sv in self.step_inputs}
        step_locals.update(m[0].name for m in self.memories)
        read = set()
        for op in self._sub.ops:
            read.update(n for n in op.desc.input_names() if n)
        params = sorted(
            n for n in read
            if n not in step_locals
            and n not in self._sub.vars
            and self._parent._var_recursive(n) is not None
        )
        self._out_vars = [
            self._parent.create_var(
                name=o.name + "@seq", dtype=o.dtype,
                shape=[o.shape[0], -1] + list(o.shape[1:]) if o.shape else None,
            )
            for o in self.outputs
        ]
        self._parent.append_op(
            type="recurrent",
            inputs={
                "StepInputs": [x for x, _ in self.step_inputs],
                "MemInit": [m[2] for m in self.memories],
                "Params": params,
            },
            outputs={"Out": self._out_vars},
            attrs={
                "sub_block": self._sub.idx,
                "step_input_vars": [sv.name for _, sv in self.step_inputs],
                "memory_links": [[m[0].name, m[1].name] for m in self.memories],
                "step_output_vars": [o.name for o in self.outputs],
                "param_var_names": params,
            },
        )

    def __call__(self):
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars
