"""Gradient clipping framework (reference python/paddle/fluid/clip.py:
GradientClipByValue:101, ByNorm:122, ByGlobalNorm:137, set_gradient_clip:184,
append_gradient_clip_ops:215) + error clip."""
from __future__ import annotations

from .framework import default_main_program


class BaseErrorClipAttr:
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def append_clip_op(self, block, grad_name):
        block.append_op(
            type="clip", inputs={"X": [grad_name]}, outputs={"Out": [grad_name]},
            attrs={"min": self.min, "max": self.max},
        )


def error_clip_callback(block, context):
    pass  # hook point kept for API parity


class BaseGradientClipAttr:
    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def create_operators(self, param, grad):
        from .layers.nn import clip as clip_layer

        return param, clip_layer(grad, min=self.min, max=self.max)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def create_operators(self, param, grad):
        from .layers.nn import clip_by_norm

        return param, clip_by_norm(grad, max_norm=self.clip_norm)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        from .layer_helper import LayerHelper

        helper = LayerHelper("global_norm")
        sq = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            type="squared_l2_norm", inputs={"X": [grad]}, outputs={"Out": [sq]}
        )
        context[self.group_name].append(sq)
        self.context = context

    def create_operators(self, param, grad):
        from .layers import nn, ops, tensor

        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm = tensor.sums(self.context[self.group_name])
            group_norm = ops.sqrt(group_norm)
            clip_var = tensor.fill_constant(
                shape=[1], dtype=group_norm.dtype, value=self.clip_norm
            )
            scale = nn.elementwise_div(
                x=clip_var, y=nn.elementwise_max(x=clip_var, y=group_norm)
            )
            self.context[group_scale_name] = scale
        new_grad = nn.elementwise_mul(x=grad, y=self.context[group_scale_name])
        return param, new_grad


def set_gradient_clip(clip, param_list=None, program=None):
    program = program or default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [
        program.global_block().var(p) if isinstance(p, str) else p
        for p in param_list
    ]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grad):
    context = {}
    clipped = []
    any_clip = False
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is None:
            continue
        any_clip = True
        clip_attr.process_context(context=context, param=p, grad=g)
    if not any_clip:
        return param_grad
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None) or NullGradientClipAttr()
        clipped.append(clip_attr.create_operators(param=p, grad=g))
    return clipped
