"""Profiler (reference python/paddle/fluid/profiler.py + platform/profiler.cc):
host RecordEvent table + TPU trace export.

The reference aggregates per-op host/CUDA timings into a table and exports
chrome://tracing JSON via CUPTI (device_tracer.cc, tools/timeline.py). Under
XLA the per-op boundary is fused away, so the equivalents are:
  - RecordEvent/profiler(): host-side named spans, aggregated table output
  - jax.profiler traces (xplane) for device timelines, viewable in
    TensorBoard/Perfetto — the chrome-trace role.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

import jax

_events = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # calls,total,min,max
_enabled = False


class RecordEvent:
    """RAII span (reference platform/profiler.h:73)."""

    def __init__(self, name: str):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not _enabled:
            return False
        dt = (time.perf_counter() - self._t0) * 1000.0
        rec = _events[self.name]
        rec[0] += 1
        rec[1] += dt
        rec[2] = min(rec[2], dt)
        rec[3] = max(rec[3], dt)
        return False


def reset_profiler():
    _events.clear()


def _print_table(sorted_key: Optional[str]):
    rows = [
        (name, c, total, total / max(c, 1), mn, mx)
        for name, (c, total, mn, mx) in _events.items()
    ]
    key_idx = {"calls": 1, "total": 2, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key or "total", 2
    )
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    print(f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Ave(ms)':>10}"
          f"{'Min(ms)':>10}{'Max(ms)':>10}")
    for name, c, total, ave, mn, mx in rows:
        print(f"{name:<40}{c:>8}{total:>12.3f}{ave:>10.3f}{mn:>10.3f}{mx:>10.3f}")


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: Optional[str] = None):
    """reference fluid/profiler.py:76. With profile_path, also captures a
    jax.profiler device trace (xplane) into that directory."""
    global _enabled
    _enabled = True
    reset_profiler()
    trace_ctx = (
        jax.profiler.trace(profile_path) if profile_path else contextlib.nullcontext()
    )
    with trace_ctx:
        try:
            yield
        finally:
            _enabled = False
            _print_table(sorted_key)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Name kept for reference API parity (fluid/profiler.py:33); maps to a
    device trace under JAX."""
    with jax.profiler.trace(output_file or "/tmp/paddle_tpu_trace"):
        yield


def start_profiler(state: str = "All"):
    global _enabled
    _enabled = True


def stop_profiler(sorted_key=None, profile_path=None):
    global _enabled
    _enabled = False
    _print_table(sorted_key)
