"""Profiler (reference python/paddle/fluid/profiler.py + platform/profiler.cc):
host RecordEvent table + chrome-trace export, re-implemented on top of
paddle_tpu.observability.

The reference aggregates per-op host/CUDA timings into a table and exports
chrome://tracing JSON via CUPTI (device_tracer.cc + tools/timeline.py).
Here the observability trace recorder plays the device_tracer role: every
RecordEvent (and every framework-internal span — executor steps, RPC
calls, reader pops) lands in one ring buffer, and `profiler(profile_path=
...)` exports it as chrome://tracing JSON loadable in Perfetto. The
aggregated table output and the RecordEvent/profiler()/start_profiler()
API are preserved exactly.

Timing-loss fix (ISSUE 1 satellite): enable-state is captured at
`__enter__`, not checked at `__exit__` — a span straddling
stop_profiler() is counted in the table it STARTED under instead of being
silently dropped, and start_profiler() resets aggregation state like the
reference's profiler begin does.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

from ..observability import tracing

_events = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # calls,total,min,max
_enabled = False


class RecordEvent:
    """RAII span (reference platform/profiler.h:73). Feeds BOTH the
    aggregated table and the observability trace buffer."""

    def __init__(self, name: str):
        self.name = name
        self._t0 = None
        self._armed = False
        self._span = None

    def __enter__(self):
        # capture enable-state NOW: a span that straddles stop_profiler()
        # belongs to the profile it started under (checking at __exit__
        # lost it entirely — satellite fix)
        self._armed = _enabled
        self._span = tracing.span(self.name)
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = (time.perf_counter() - self._t0) * 1000.0
        self._span.__exit__(*exc)
        if not self._armed:
            return False
        rec = _events[self.name]
        rec[0] += 1
        rec[1] += dt
        rec[2] = min(rec[2], dt)
        rec[3] = max(rec[3], dt)
        return False


def reset_profiler():
    _events.clear()


def _print_table(sorted_key: Optional[str]):
    rows = [
        (name, c, total, total / max(c, 1), mn, mx)
        for name, (c, total, mn, mx) in _events.items()
    ]
    key_idx = {"calls": 1, "total": 2, "ave": 3, "min": 4, "max": 5}.get(
        sorted_key or "total", 2
    )
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    print(f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Ave(ms)':>10}"
          f"{'Min(ms)':>10}{'Max(ms)':>10}")
    for name, c, total, ave, mn, mx in rows:
        print(f"{name:<40}{c:>8}{total:>12.3f}{ave:>10.3f}{mn:>10.3f}{mx:>10.3f}")


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: Optional[str] = None):
    """reference fluid/profiler.py:76. With profile_path, exports the
    scope's spans (RecordEvents + executor/RPC/reader instrumentation) as
    chrome://tracing JSON to that path — open it in Perfetto
    (ui.perfetto.dev) or chrome://tracing. A directory path gets
    <dir>/trace.json (the old xplane-directory contract)."""
    global _enabled
    _enabled = True
    reset_profiler()
    was_tracing = tracing.trace_enabled()
    tracing.trace_enable()
    if not was_tracing:
        tracing.trace_reset()
    try:
        yield
    finally:
        _enabled = False
        if profile_path:
            tracing.trace_export(profile_path)
        if not was_tracing:
            tracing.trace_disable()
        _print_table(sorted_key)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Name kept for reference API parity (fluid/profiler.py:33); maps to a
    device trace under JAX (xplane, viewable in TensorBoard/Perfetto)."""
    import jax

    with jax.profiler.trace(output_file or "/tmp/paddle_tpu_trace"):
        yield


# was span recording already on (env flag / explicit trace_enable) when
# start_profiler turned it on? stop_profiler restores that state instead
# of leaving the recorder running process-wide forever. None = no
# start_profiler pending — an unpaired stop_profiler() must NOT touch a
# session someone else (PADDLE_TPU_TRACE, trace_enable) started.
_prev_tracing: Optional[bool] = None


def start_profiler(state: str = "All"):
    """reference fluid/profiler.py:51 — begins a fresh profile: resets
    aggregation (the reference's EnableProfiler starts a new recording)
    and turns span recording on."""
    global _enabled, _prev_tracing
    reset_profiler()
    _enabled = True
    if _prev_tracing is None:  # nested starts keep the OUTERMOST state
        _prev_tracing = tracing.trace_enabled()
    tracing.trace_enable()
    if not _prev_tracing:
        tracing.trace_reset()


def stop_profiler(sorted_key=None, profile_path=None):
    global _enabled, _prev_tracing
    _enabled = False
    if profile_path:
        tracing.trace_export(profile_path)
    if _prev_tracing is False:
        tracing.trace_disable()
    _prev_tracing = None
    _print_table(sorted_key)
