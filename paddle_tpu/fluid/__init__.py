"""fluid-compatible API surface (reference python/paddle/fluid/__init__.py)."""
from . import core  # noqa: F401
from . import ops as _ops  # registers all op emitters  # noqa: F401
from . import (  # noqa: F401
    average,
    backward,
    clip,
    concurrency,
    default_scope_funcs,
    enforce,
    evaluator,
    initializer,
    io,
    layers,
    metrics,
    nets,
    optimizer,
    param_attr,
    profiler,
    recordio_writer,
    regularizer,
    unique_name,
)
from .enforce import EnforceNotMet  # noqa: F401
from .distribute_transpiler import DistributeTranspiler  # noqa: F401
from .memory_optimization_transpiler import (  # noqa: F401
    memory_optimize,
    release_memory,
)
from .backward import append_backward, calc_gradient  # noqa: F401
from . import debugger, graphviz, net_drawer  # noqa: F401
from .clip import (  # noqa: F401
    ErrorClipByValue,
    GradientClipByGlobalNorm,
    GradientClipByNorm,
    GradientClipByValue,
    set_gradient_clip,
)
from .core import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .executor import (  # noqa: F401
    Executor,
    Scope,
    fetch_var,
    global_scope,
    scope_guard,
)
from .framework import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
)
from .initializer import Constant, MSRA, Normal, Uniform, Xavier  # noqa: F401
from .io import (  # noqa: F401
    load_checkpoint,
    load_inference_model,
    load_params,
    load_persistables,
    load_vars,
    save_checkpoint,
    save_inference_model,
    save_params,
    save_persistables,
    save_vars,
)
from .parallel_executor import ParallelExecutor  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

Tensor = None  # runtime tensors are jax.Arrays; alias kept for API scripts
