"""ParallelExecutor — multi-chip data-parallel training.

Capability-parity with the reference ParallelExecutor
(`paddle/fluid/framework/parallel_executor.cc:50`,
`python/paddle/fluid/parallel_executor.py:23`), redesigned for XLA SPMD:

  - The reference replicates the op graph per GPU, seeds 1/N loss grads, and
    inserts NCCLAllReduceOpHandle per param-grad into a threaded SSA dataflow
    graph (multi_devices_graph_builder.cc:167).
  - Here the SAME lowered block function is jit-compiled with
    jax.sharding: feed arrays are sharded on the batch axis of a device
    Mesh, persistable state is replicated, and XLA's SPMD partitioner
    inserts the ICI all-reduces where the gradient computation crosses the
    sharded batch dimension. The dataflow overlap the reference got from
    threads, XLA gets from async collectives in one program.

API preserved: ParallelExecutor(use_cuda, loss_name).run(fetch_list, feed).
"""
from __future__ import annotations

import time as _time
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import core
from ..observability import metrics as _metrics, tracing as _tracing
from .enforce import throw_on
from .executor import Scope, _block_io, _lower, _next_seed, global_scope
from .framework import Program, Variable, default_main_program

# per-step latency over the sharded executable. Under SPMD the gradient
# all-reduce is INSIDE the step program (XLA inserts the ICI collectives
# where the grad computation crosses the sharded batch dim), so
# grad_allreduce_step_ms — observed only for runs dispatching a training
# step (loss_name set) — is the collective-inclusive step time, the
# number the reference's per-NCCLAllReduceOpHandle timers added up to.
_m_pe_step_ms = _metrics.histogram("parallel_executor.step_ms")
_m_pe_allreduce_ms = _metrics.histogram(
    "parallel_executor.grad_allreduce_step_ms")
_m_pe_compiles = _metrics.counter("parallel_executor.jit_compiles")
_m_pe_cache_hits = _metrics.counter("parallel_executor.jit_cache_hits")


def _as_name(v) -> str:
    return v.name if isinstance(v, Variable) else str(v)


def _spans_processes(mesh: Mesh) -> bool:
    """True when the mesh includes devices of OTHER processes (multi-host:
    one SPMD program over DCN, reference capability = the trainer fleet)."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _global_state_put(mesh: Mesh, arr, spec):
    """Place state every process holds IN FULL onto a cross-process mesh:
    each process contributes the shards its local devices own (params are
    replicated or plan-sharded; either way the full value is available
    host-side, so indexing out the local piece is exact)."""
    sharding = NamedSharding(mesh, spec)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


class ParallelExecutor:
    def __init__(
        self,
        use_cuda: Optional[bool] = None,
        loss_name: Optional[str] = None,
        main_program: Optional[Program] = None,
        num_threads: Optional[int] = None,
        allow_op_delay: bool = False,
        share_vars_from: Optional["ParallelExecutor"] = None,
        devices: Optional[Sequence[Any]] = None,
        use_tpu: Optional[bool] = None,
        mesh: Optional[Mesh] = None,
        sharding_plan=None,
        collect_cost: bool = False,
    ):
        """`collect_cost`: compile through the AOT path and expose XLA's
        cost analysis of the sharded executable as
        `self.last_cost_analysis` ({"flops", "bytes_accessed"}) — the
        dryrun records these per phase so a communication/remat regression
        shows up as a number, not just a slower wall clock."""
        from ..parallel import ShardingPlan

        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        if mesh is not None:
            if not isinstance(mesh, Mesh):
                # MeshSpec / axes dict / "dp=2,tp=4" string (ISSUE 15):
                # the mesh layer's one coercion rule, built here
                from ..mesh import MeshSpec

                mesh = MeshSpec.coerce(mesh).build(devices=devices)
            self._mesh = mesh
        else:
            from .flags import FLAGS

            if FLAGS["mesh_axes"]:
                # operator-configured default mesh: a run that passes
                # no mesh= still trains sharded per the flag
                from ..mesh import MeshSpec

                self._mesh = MeshSpec.parse(
                    FLAGS["mesh_axes"]).build(devices=devices)
            else:
                devs = (list(devices) if devices is not None
                        else jax.devices())
                self._mesh = Mesh(np.asarray(devs), ("dp",))
        self._plan = sharding_plan or ShardingPlan(batch_axis=self._mesh.axis_names[0])
        self._sharded = int(self._mesh.devices.size) > 1
        if self._sharded:
            from ..mesh import note_mesh

            note_mesh(self._mesh, label="parallel_executor")
        self._scope = (
            share_vars_from._scope if share_vars_from is not None else global_scope()
        )
        self._cache: Dict[Any, Any] = {}
        self._collect_cost = bool(collect_cost)
        self.last_cost_analysis: Optional[Dict[str, float]] = None

    @property
    def device_count(self) -> int:
        return self._mesh.devices.size

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy: bool = True):
        feed = feed if feed is not None else feed_dict
        feed = feed or {}
        if isinstance(feed, (list, tuple)):
            # reference accepts per-device feed dicts; concat on batch dim
            merged: Dict[str, Any] = {}
            for d in feed:
                for k, v in d.items():
                    merged.setdefault(k, []).append(np.asarray(v))
            feed = {k: np.concatenate(v, axis=0) for k, v in merged.items()}

        program = self._program
        block = program.global_block()
        fetch_names = tuple(_as_name(v) for v in fetch_list)
        mesh = self._mesh

        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

        def _divisible(shape, spec):
            # every sharded dim must divide by its mesh-axis size
            for dim, ax in enumerate(spec):
                if ax is None:
                    continue
                axes = ax if isinstance(ax, tuple) else (ax,)
                size = int(np.prod([axis_sizes.get(a, 1) for a in axes]))
                if dim >= len(shape) or shape[dim] % size != 0:
                    return False
            return True

        def _resolve_spec(name, shape):
            """Plan spec for a state var. Size-1 arrays (scalar optimizer
            accumulators whose names match a param rule) fall back to
            replication; a genuinely indivisible param is a misconfigured
            plan and fails loudly — except under a best_effort plan
            (plan_fsdp's catch-all: real FSDP replicates the odd-width
            biases and class-count tails it cannot split evenly)."""
            spec = self._plan.spec_for(name, len(shape))
            if _divisible(shape, spec):
                return spec
            if int(np.prod(shape, dtype=np.int64)) <= 1:
                return P(*([None] * len(shape)))
            if getattr(self._plan, "best_effort", False):
                return P(*([None] * len(shape)))
            throw_on(
                "sharding plan maps var '%s' (shape %s) to %s, but a "
                "dimension does not divide the mesh axis size %s — fix the "
                "plan rules or the model dims",
                name, tuple(shape), spec, axis_sizes,
                context="ParallelExecutor",
            )

        multiproc = _spans_processes(mesh)
        feed_arrays = {}
        for k, v in feed.items():
            arr = np.asarray(v)
            spec = self._plan.feed_spec(arr.ndim)
            if multiproc:
                # each process feeds its LOCAL batch shard; jax assembles
                # the global array (global batch = concat over processes —
                # the reference trainer fleet's per-trainer minibatches)
                try:
                    feed_arrays[k] = jax.make_array_from_process_local_data(
                        NamedSharding(mesh, spec), arr)
                except (ValueError, TypeError) as e:
                    # replicating a per-process-different feed would be
                    # silently wrong — fail with the fix spelled out
                    throw_on(
                        "feed '%s' local shape %s does not shard over the "
                        "multi-host mesh %s (%s) — pad the local batch or "
                        "use drop_last so every process feeds an equal, "
                        "divisible shard",
                        k, tuple(arr.shape), dict(axis_sizes), e,
                        context="ParallelExecutor",
                    )
                continue
            if not (arr.shape and self._plan.batch_axis
                    and _divisible(arr.shape, spec)):
                # indivisible feeds stay replicated (reference PE pads/splits)
                spec = P(*([None] * arr.ndim))
            feed_arrays[k] = jax.device_put(arr, NamedSharding(mesh, spec))

        feed_sig = tuple(
            sorted((k, tuple(v.shape), str(v.dtype)) for k, v in feed_arrays.items())
        )
        from .flags import FLAGS, trace_flags

        cache_key = (id(program), program._version, feed_sig, fetch_names,
                     trace_flags())
        entry = self._cache.get(cache_key)
        fresh_compile = entry is None
        if entry is not None:
            _m_pe_cache_hits.inc()
        if entry is None:
            _m_pe_compiles.inc()
            state_in, state_out = _block_io(block, set(feed_arrays), self._scope)
            missing = [n for n in state_in if not self._scope.has_var(n)]
            if missing:
                raise RuntimeError(
                    f"vars {missing} not initialized — run the startup program "
                    "with a plain Executor first"
                )
            fn, ro_names, rw_names = _lower(
                block, tuple(feed_arrays), fetch_names, tuple(state_in),
                tuple(state_out),
            )
            def _state_spec(n):
                shape = np.shape(self._scope.find_var(n))  # metadata only
                return NamedSharding(mesh, _resolve_spec(n, shape))

            out_state_shardings = {n: _state_spec(n) for n in state_out}
            jfn = jax.jit(
                fn,
                donate_argnums=(2,),
                out_shardings=(None, out_state_shardings),
            )
            entry = {"jfn": jfn, "ro": ro_names, "rw": rw_names,
                     "state_out": tuple(state_out), "compiled": None,
                     "cost": None, "collectives": None}
            self._cache[cache_key] = entry

        jfn, ro_names, rw_names, state_out = (
            entry["jfn"], entry["ro"], entry["rw"], entry["state_out"])

        def _place(name, x):
            if multiproc:
                if isinstance(x, jax.Array) and not x.is_fully_addressable:
                    return x  # already a global array from a prior step
                return _global_state_put(
                    mesh, x, _resolve_spec(name, np.shape(x)))
            x = jnp.asarray(x)
            target = NamedSharding(mesh, _resolve_spec(name, x.shape))
            if getattr(x, "sharding", None) == target:
                return x
            return jax.device_put(x, target)

        state_ro = {n: _place(n, self._scope.find_var(n)) for n in ro_names}
        state_rw = {n: _place(n, self._scope.find_var(n)) for n in rw_names}
        seed = _next_seed(program)
        from ..parallel import mesh_context

        # emitters that need explicit SPMD (ring attention) see the mesh
        # during tracing, which happens inside this first call
        t0 = _time.perf_counter()
        collectives = None
        with mesh_context(mesh), _tracing.span(
                "parallel_executor.step", devices=int(mesh.devices.size),
                program_version=program._version) as _step_span:
            if self._collect_cost or self._sharded:
                # AOT path: sharded runs always lower explicitly so the
                # compiled program's COLLECTIVES can be counted exactly
                # (mesh.collectives.* — the number a communication
                # regression moves; wall clocks on a contended host
                # cannot carry that evidence), collect_cost additionally
                # records XLA's flop/byte analysis
                if entry["compiled"] is None:
                    compiled = jfn.lower(
                        feed_arrays, state_ro, state_rw, seed).compile()
                    if self._sharded:
                        # count from the COMPILED text: the SPMD
                        # partitioner inserts collectives after
                        # StableHLO, so the lowered form has none yet
                        from ..mesh import note_sharded_compile

                        try:
                            hlo = compiled.as_text()
                        except Exception:  # pragma: no cover - backend
                            hlo = ""
                        entry["collectives"] = note_sharded_compile(hlo)
                    entry["compiled"] = compiled
                    if self._collect_cost:
                        from ..jax_compat import cost_analysis_dict

                        ca = cost_analysis_dict(compiled)
                        entry["cost"] = {
                            "flops": float(ca.get("flops", -1.0)),
                            "bytes_accessed": float(
                                ca.get("bytes accessed", -1.0)),
                        }
                self.last_cost_analysis = entry["cost"]
                collectives = entry["collectives"]
                fetches, new_state = entry["compiled"](
                    feed_arrays, state_ro, state_rw, seed)
            else:
                fetches, new_state = jfn(feed_arrays, state_ro, state_rw,
                                         seed)
            if self._sharded:
                from ..mesh import sharded_step_counter

                sharded_step_counter().inc()
                if collectives:
                    # the span carries the compiled program's collective
                    # census, so a trace shows what each step ships
                    # over ICI without a device profiler
                    _step_span.set_arg(
                        "collectives", int(sum(collectives.values())))
        step_ms = (_time.perf_counter() - t0) * 1e3
        _m_pe_step_ms.observe(step_ms)
        if self._loss_name:  # a training step: includes the grad all-reduce
            _m_pe_allreduce_ms.observe(step_ms)
        for n, v in new_state.items():
            self._scope.set_var(n, v)
        if return_numpy:
            from .selected_rows import is_selected_rows

            out = [f if is_selected_rows(f) else np.asarray(f)
                   for f in fetches]
            if FLAGS["autotune"] and not fresh_compile:
                # same per-shape step log the single-device executor
                # feeds (ISSUE 8). Logged AFTER the numpy conversion —
                # np.asarray is the only honest device barrier
                # (block_until_ready lies through the axon tunnel,
                # benchmarks/_timing.py); timing the bare jfn() return
                # would persist async-DISPATCH latency as the step
                # cost. Compile runs excluded; return_numpy=False runs
                # have no barrier, so they are not logged at all.
                from ..autotune.measure import note_step_timing

                try:
                    note_step_timing(
                        "parallel_executor.step", program, feed,
                        (_time.perf_counter() - t0) * 1e3)
                except Exception:
                    pass
            return out
        return list(fetches)

    def bcast_params(self):
        """Parity with reference bcast_params (parallel_executor.py:149):
        re-replicate scope params over the mesh (cross-process meshes go
        through the local-shard contribution path, like run())."""
        mesh = self._mesh
        multiproc = _spans_processes(mesh)
        with _tracing.span("parallel_executor.bcast_params",
                           devices=int(mesh.devices.size)):
            self._bcast_params_body(mesh, multiproc)

    def _bcast_params_body(self, mesh, multiproc):
        for name in list(self._scope.var_names()):
            v = self._scope.find_var(name)
            if multiproc:
                if isinstance(v, jax.Array) and not v.is_fully_addressable:
                    continue  # already global
                self._scope.set_var(
                    name,
                    _global_state_put(mesh, v, P(*([None] * np.ndim(v)))),
                )
                continue
            arr = jnp.asarray(v)
            self._scope.set_var(
                name,
                jax.device_put(arr, NamedSharding(mesh, P(*([None] * arr.ndim)))),
            )
