"""Program -> graphviz rendering (reference python/paddle/fluid/
net_drawer.py draw_graph): walks a Program's desc the same way
debugger.to_code does, emitting a styled dataflow graph through
graphviz.GraphPreviewGenerator. Usable as a module
(`python -m paddle_tpu.fluid.net_drawer model.pb -o graph.dot`)."""
from __future__ import annotations

import argparse
from typing import Optional

from .framework import Parameter, Program
from .graphviz import GraphPreviewGenerator

__all__ = ["draw_graph", "draw_program"]


def draw_program(program: Program, title: str = "program",
                 block_idx: int = 0) -> GraphPreviewGenerator:
    """Build the preview graph for one block: ops as ellipses, params as
    filled boxes, temps dashed; edges follow the op input/output lists
    (reference net_drawer.parse_graph)."""
    g = GraphPreviewGenerator(title)
    block = program.block(block_idx)
    var_nodes = {}

    def var_node(name):
        if name in var_nodes:
            return var_nodes[name]
        var = block._var_recursive(name)
        shape = tuple(var.shape) if var is not None and var.shape else None
        dtype = var.dtype if var is not None else None
        if isinstance(var, Parameter):
            n = g.add_param(name, dtype, shape)
        else:
            n = g.add_var(name, dtype, shape)
        var_nodes[name] = n
        return n

    for op in block.ops:
        od = op.desc
        op_node = g.add_op(od.type)
        for name in od.input_names():
            if name:
                g.add_edge(var_node(name), op_node)
        for name in od.output_names():
            if name:
                g.add_edge(op_node, var_node(name))
    return g


def draw_graph(startup_program: Program, main_program: Program,
               dot_path: str = "graph.dot",
               image_path: Optional[str] = None, **kwargs):
    """reference net_drawer.py:draw_graph — renders the MAIN program (the
    startup program only carries initializers; the reference draws the
    same)."""
    g = draw_program(main_program, title=kwargs.get("graph_attr", {}).get(
        "label", "main_program") if isinstance(
            kwargs.get("graph_attr"), dict) else "main_program")
    g(dot_path, image_path)
    return g


def main():
    parser = argparse.ArgumentParser(
        description="render a serialized Program to graphviz dot")
    parser.add_argument("model", help="path to a serialized ProgramDesc "
                        "(Program.to_bytes output / __model__ file)")
    parser.add_argument("-o", "--output", default="graph.dot")
    parser.add_argument("--image", default=None)
    args = parser.parse_args()
    with open(args.model, "rb") as f:
        program = Program.parse_from_bytes(f.read())
    draw_program(program)(args.output, args.image)
    print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
