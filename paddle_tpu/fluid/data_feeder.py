"""DataFeeder (reference python/paddle/fluid/data_feeder.py:69) — converts
per-sample python/numpy data into batched feed arrays."""
from __future__ import annotations

import numpy as np

from .core import convert_dtype
from .framework import Variable


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [d for d in shape]
        self.dtype = dtype
        self.data = []
        self.lod = [[0] for _ in range(lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(lod[0][-1] + len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self):
        arr = np.array(self.data, dtype=self.dtype)
        shape = [d if d >= 0 else -1 for d in self.shape]
        if self.lod_level == 0 and shape and any(d == -1 for d in shape):
            arr = arr.reshape([arr.shape[0]] + [d for d in shape[1:]])
        return arr


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        from .framework import default_main_program

        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should contain Variables or names")
            self.feed_dtypes.append(np.dtype(convert_dtype(each_var.dtype))
                                    if each_var.dtype != "bfloat16" else np.float32)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(self.place, lod_level, shape, dtype)
            for lod_level, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes
            )
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                f"sample has {len(each_sample)} slots, expected {len(converters)}"
            )
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        return {
            name: conv.done()
            for name, conv in zip(self.feed_names, converters)
        }
