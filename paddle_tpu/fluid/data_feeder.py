"""DataFeeder (reference python/paddle/fluid/data_feeder.py:69) — converts
per-sample python/numpy data into batched feed arrays."""
from __future__ import annotations

import numpy as np

from .core import convert_dtype
from .framework import Variable


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [d for d in shape]
        self.dtype = dtype
        self.data = []

    def feed(self, data):
        # lod_level>0: keep the ragged sample whole; done() pads + lengths
        self.data.append(data)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            shape = [d if d >= 0 else -1 for d in self.shape]
            # conform samples to the declared var shape (reference feeds flat
            # reader rows into e.g. [1,28,28] data vars)
            if shape[1:] and tuple(arr.shape[1:]) != tuple(shape[1:]):
                arr = arr.reshape([arr.shape[0]] + [d for d in shape[1:]])
            return arr
        if self.lod_level > 1:
            raise NotImplementedError(
                "nested (lod_level>1) sequences: flatten or bucket upstream"
            )
        # ragged -> padded [N, T, ...] + lengths, T bucketed to a power of two
        # to bound recompilations (XLA static shapes; SURVEY.md §5.7)
        seqs = [np.asarray(s, dtype=self.dtype) for s in self.data]
        lengths = np.asarray([len(s) for s in seqs], dtype=np.int32)
        max_len = max(1, int(lengths.max()))
        T = 8
        while T < max_len:
            T *= 2
        item_shape = ()
        for s in seqs:  # first non-empty sample defines the item shape
            if len(s):
                item_shape = s.shape[1:]
                break
        padded = np.zeros((len(seqs), T) + item_shape, dtype=self.dtype)
        for i, s in enumerate(seqs):
            if len(s):
                padded[i, :len(s)] = s
        return padded, lengths


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        from .framework import default_main_program

        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should contain Variables or names")
            self.feed_dtypes.append(np.dtype(convert_dtype(each_var.dtype))
                                    if each_var.dtype != "bfloat16" else np.float32)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(self.place, lod_level, shape, dtype)
            for lod_level, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes
            )
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                f"sample has {len(each_sample)} slots, expected {len(converters)}"
            )
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        out = {}
        for name, conv in zip(self.feed_names, converters):
            res = conv.done()
            if isinstance(res, tuple):
                out[name], out[name + "@LEN"] = res
            else:
                out[name] = res
        return out
