"""Pure-Python metric accumulators (reference python/paddle/fluid/metrics.py):
host-side state updated from fetched numpy values each step — complementary to
the in-graph metric ops (accuracy/auc/... emitters)."""
from __future__ import annotations

import copy

import numpy as np

__all__ = [
    "MetricBase",
    "CompositeMetric",
    "Accuracy",
    "ChunkEvaluator",
    "EditDistance",
    "DetectionMAP",
    "Auc",
]


def _is_numpy_(var):
    return isinstance(var, (np.ndarray, np.generic))


def _is_number_(var):
    return isinstance(var, (int, float, np.number)) or (
        _is_numpy_(var) and var.size == 1
    )


def _is_number_or_matrix_(var):
    return _is_number_(var) or _is_numpy_(var)


class MetricBase:
    """State container: reset() zeroes every non-private attribute;
    update(...) folds a batch in; eval() returns the metric value."""

    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }
        for attr, value in states.items():
            if isinstance(value, int):
                setattr(self, attr, 0)
            elif isinstance(value, float):
                setattr(self, attr, 0.0)
            elif isinstance(value, (np.ndarray, np.generic)):
                setattr(self, attr, np.zeros_like(value))
            else:
                setattr(self, attr, None)

    def get_config(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }
        config = {}
        config.update({"name": self._name, "states": copy.deepcopy(states)})
        return config

    def update(self, preds, labels):
        raise NotImplementedError()

    def eval(self):
        raise NotImplementedError()


class CompositeMetric(MetricBase):
    """Fan one (pred, label) stream into several metrics."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("metric should be a MetricBase")
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    """Weighted running accuracy: update(batch_accuracy, batch_size)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        if not _is_number_or_matrix_(value):
            raise ValueError("value should be a number or a numpy array")
        if not _is_number_(weight):
            raise ValueError("weight should be a number")
        self.value += float(np.asarray(value).reshape(-1)[0]) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("accuracy has no data; call update() first")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunking precision/recall/F1 from per-batch counts (reference feeds it
    the chunk_eval op's NumInferChunks/NumLabelChunks/NumCorrectChunks)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        for v in (num_infer_chunks, num_label_chunks, num_correct_chunks):
            if not _is_number_or_matrix_(v):
                raise ValueError("chunk counts must be numbers")
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).reshape(-1)[0]
        )

    def eval(self):
        precision = (
            float(self.num_correct_chunks) / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            float(self.num_correct_chunks) / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1_score = (
            2 * precision * recall / (precision + recall)
            if self.num_correct_chunks
            else 0.0
        )
        return precision, recall, f1_score


class EditDistance(MetricBase):
    """Average edit distance + instance error rate from the edit_distance op's
    (distances, seq_num) per batch."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        if not _is_numpy_(np.asarray(distances)):
            raise ValueError("distances should be a numpy array")
        distances = np.asarray(distances, dtype=np.float64)
        seq_num = int(np.asarray(seq_num).reshape(-1)[0])
        self.seq_num += seq_num
        self.instance_error += int(np.sum(distances > 0))
        self.total_distance += float(np.sum(distances))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data in EditDistance; call update() first")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class DetectionMAP(MetricBase):
    """Running mean of per-batch mAP values (the in-graph detection_map op
    computes the per-batch value)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if not _is_number_or_matrix_(value):
            raise ValueError("value should be a number or a numpy array")
        if not _is_number_(weight):
            raise ValueError("weight should be a number")
        self.value += float(np.asarray(value).reshape(-1)[0]) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("DetectionMAP has no data; call update() first")
        return self.value / self.weight


class Auc(MetricBase):
    """Streaming AUC over `num_thresholds` confusion-count bins; update() takes
    raw (preds, labels) with preds[:, 1] the positive-class score."""

    def __init__(self, name=None, curve="ROC", num_thresholds=200):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._epsilon = 1e-6
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def update(self, preds, labels):
        if not _is_numpy_(np.asarray(labels)):
            raise ValueError("labels should be a numpy array")
        if not _is_numpy_(np.asarray(preds)):
            raise ValueError("preds should be a numpy array")
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1).astype(bool)
        pos_score = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        kepsilon = self._epsilon
        thresholds = [
            (i + 1) * 1.0 / (self._num_thresholds - 1)
            for i in range(self._num_thresholds - 2)
        ]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        for idx_thresh, thresh in enumerate(thresholds):
            pred_pos = pos_score >= thresh
            self.tp_list[idx_thresh] += np.sum(pred_pos & labels)
            self.fp_list[idx_thresh] += np.sum(pred_pos & ~labels)
            self.fn_list[idx_thresh] += np.sum(~pred_pos & labels)
            self.tn_list[idx_thresh] += np.sum(~pred_pos & ~labels)

    def eval(self):
        epsilon = self._epsilon
        num_thresholds = self._num_thresholds
        tpr = (self.tp_list.astype("float32") + epsilon) / (
            self.tp_list + self.fn_list + epsilon
        )
        fpr = self.fp_list.astype("float32") / (
            self.fp_list + self.tn_list + epsilon
        )
        precision = (self.tp_list.astype("float32") + epsilon) / (
            self.tp_list + self.fp_list + epsilon
        )

        if self._curve == "PR":
            # integrate precision over recall (tpr == recall here)
            x = tpr[:num_thresholds - 1] - tpr[1:]
            y = (precision[:num_thresholds - 1] + precision[1:]) / 2.0
        else:
            x = fpr[:num_thresholds - 1] - fpr[1:]
            y = (tpr[:num_thresholds - 1] + tpr[1:]) / 2.0
        return np.sum(x * y)
