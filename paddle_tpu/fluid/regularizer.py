"""Weight-decay regularizers appended as ops on gradients (reference
python/paddle/fluid/regularizer.py:25,101,155)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def append_regularization_ops(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._regularization_coeff = float(regularization_coeff)

    def __call__(self, param, grad, block):
        decay = block.create_var(
            name=param.name + ".l2decay", dtype=param.dtype, shape=param.shape,
            stop_gradient=True,
        )
        block.append_op(
            type="scale", inputs={"X": [param]}, outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._regularization_coeff = float(regularization_coeff)

    def __call__(self, param, grad, block):
        sign = block.create_var(
            name=param.name + ".l1sign", dtype=param.dtype, shape=param.shape,
            stop_gradient=True,
        )
        block.append_op(type="sign", inputs={"X": [param]}, outputs={"Out": [sign]})
        decay = block.create_var(
            name=param.name + ".l1decay", dtype=param.dtype, shape=param.shape,
            stop_gradient=True,
        )
        block.append_op(
            type="scale", inputs={"X": [sign]}, outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """reference regularizer.py append_regularization_ops: grad += decay."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        regularization_term = None
        reg = getattr(param, "regularizer", None) or regularization
        if grad is None or reg is None:
            params_and_grads.append((param, grad))
            continue
        block = grad.block
        regularization_term = reg(param, grad, block)
        new_grad = block.create_var(
            name=grad.name + ".reg", dtype=param.dtype, shape=param.shape,
            stop_gradient=True,
        )
        block.append_op(
            type="sum",
            inputs={"X": [grad, regularization_term]},
            outputs={"Out": [new_grad]},
        )
        params_and_grads.append((param, new_grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
