"""Serializable program IR descs.

Capability-parity with the reference's protobuf IR
(`paddle/fluid/framework/framework.proto`: OpDesc:34, VarType:94,
BlockDesc:163, ProgramDesc:176). The descs here are plain dataclasses with a
canonical JSON byte encoding — the serialized `__model__` artifact produced by
save_inference_model round-trips through these.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

IR_VERSION = 1


@dataclasses.dataclass
class VarDesc:
    name: str
    type: str = "lod_tensor"  # VarType value
    dtype: str = "float32"
    shape: Optional[List[int]] = None
    lod_level: int = 0
    persistable: bool = False
    stop_gradient: bool = False
    is_parameter: bool = False
    trainable: bool = True
    # READER vars only: per-slot {shape, dtype, lod_level} specs (the
    # reference's VarType.ReaderDesc lod_tensor list, framework.proto:94) —
    # read_file() creates its output vars from these
    reader_slots: Optional[List[Dict[str, Any]]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "VarDesc":
        return cls(**d)


@dataclasses.dataclass
class OpDesc:
    type: str
    inputs: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    outputs: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": self.attrs,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OpDesc":
        return cls(
            type=d["type"],
            inputs={k: list(v) for k, v in d.get("inputs", {}).items()},
            outputs={k: list(v) for k, v in d.get("outputs", {}).items()},
            attrs=dict(d.get("attrs", {})),
        )

    def input_names(self) -> List[str]:
        return [n for names in self.inputs.values() for n in names if n]

    def output_names(self) -> List[str]:
        return [n for names in self.outputs.values() for n in names if n]

    def rename_inputs(self, mapping: Dict[str, str]):
        if mapping:
            for slot, names in self.inputs.items():
                self.inputs[slot] = [mapping.get(n, n) for n in names]

    def rename_outputs(self, mapping: Dict[str, str]):
        if mapping:
            for slot, names in self.outputs.items():
                self.outputs[slot] = [mapping.get(n, n) for n in names]


@dataclasses.dataclass
class BlockDesc:
    idx: int = 0
    parent_idx: int = -1
    vars: Dict[str, VarDesc] = dataclasses.field(default_factory=dict)
    ops: List[OpDesc] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": {k: v.to_dict() for k, v in self.vars.items()},
            "ops": [o.to_dict() for o in self.ops],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BlockDesc":
        return cls(
            idx=d["idx"],
            parent_idx=d.get("parent_idx", -1),
            vars={k: VarDesc.from_dict(v) for k, v in d.get("vars", {}).items()},
            ops=[OpDesc.from_dict(o) for o in d.get("ops", [])],
        )


@dataclasses.dataclass
class ProgramDesc:
    blocks: List[BlockDesc] = dataclasses.field(default_factory=list)
    version: int = IR_VERSION

    def to_dict(self) -> Dict[str, Any]:
        return {"version": self.version, "blocks": [b.to_dict() for b in self.blocks]}

    def to_bytes(self) -> bytes:
        return json.dumps(self.to_dict(), sort_keys=True).encode("utf-8")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ProgramDesc":
        return cls(
            blocks=[BlockDesc.from_dict(b) for b in d.get("blocks", [])],
            version=d.get("version", IR_VERSION),
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "ProgramDesc":
        return cls.from_dict(json.loads(b.decode("utf-8")))
