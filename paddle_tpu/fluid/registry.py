"""Operator registry + emitter contract + generic reverse-mode gradient.

Capability-parity with the reference's op registry and grad-op machinery
(`paddle/fluid/framework/op_registry.h:50-195`,
`paddle/fluid/framework/grad_op_desc_maker.h`), redesigned for XLA:

  - An op is not a C++ kernel pair; it is a JAX *emitter*:
        forward(ctx, ins, attrs) -> {out_slot: [jax.Array, ...]}
    where `ins` maps input slot names to lists of arrays. The executor traces
    emitters in program order into ONE function per block and jit-compiles it,
    so XLA fuses across op boundaries (the reference's per-op kernel dispatch
    loop, executor.cc:344, disappears at runtime).

  - Gradients do not need ~125 hand-written grad kernels: a single generic
    grad emitter re-traces the forward emitter under jax.vjp. Because the
    re-traced forward lives in the same XLA computation as the original, CSE
    deduplicates it — semantically this is the reference's GradOpDescMaker,
    with XLA doing the work of `backward.cc`. Ops may still register a custom
    grad emitter (e.g. fused Pallas kernels) via `grad=`.

  - RNG-consuming ops (dropout, *_random) are deterministic functions of a
    per-op seed attr folded into the step key, so the vjp re-trace reproduces
    the same randomness (the reference stores dropout masks instead).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .enforce import enforce

# attr key carrying the forward-op metadata on generated grad ops
FWD_META_ATTR = "__fwd__"
RNG_SEED_ATTR = "__rng_seed__"
GRAD_SUFFIX = "@GRAD"


class EmitCtx:
    """Per-trace context handed to emitters (role of the reference's
    ExecutionContext, operator.h:185): RNG access, execution mode, and the
    owning Program (control-flow emitters resolve sub-blocks through it)."""

    def __init__(self, root_key=None, is_test: bool = False, program=None):
        self._root_key = root_key
        self.is_test = is_test
        self.program = program

    def rng(self, attrs: Dict[str, Any]):
        """Deterministic per-op key: fold the op's seed into the step key."""
        if self._root_key is None:
            raise RuntimeError("op requires RNG but no key was provided")
        seed = int(attrs.get("seed", 0) or 0)
        op_seed = int(attrs.get(RNG_SEED_ATTR, 0))
        return jax.random.fold_in(self._root_key, seed * 1000003 + op_seed)


class OpInfo:
    def __init__(
        self,
        type: str,
        forward: Callable,
        needs_rng: bool = False,
        grad: Optional[Callable] = None,
        infer_shape: Optional[Callable] = None,
        no_grad: Sequence[str] = (),
        ref: Optional[str] = None,
    ):
        self.type = type
        self.forward = forward
        self.needs_rng = needs_rng
        self.grad = grad
        self.infer_shape = infer_shape
        self.no_grad = frozenset(no_grad)
        self.ref = ref


OPS: Dict[str, OpInfo] = {}


def register_op(
    type: str,
    needs_rng: bool = False,
    grad: Optional[Callable] = None,
    infer_shape: Optional[Callable] = None,
    no_grad: Sequence[str] = (),
    ref: Optional[str] = None,
):
    """Decorator registering a forward emitter under an op type name
    (role of REGISTER_OPERATOR / REGISTER_OP_CUDA_KERNEL,
    op_registry.h:127,192)."""

    def deco(fn):
        enforce(type not in OPS, "op '%s' registered twice", type,
                context="register_op")
        OPS[type] = OpInfo(
            type, fn, needs_rng=needs_rng, grad=grad, infer_shape=infer_shape,
            no_grad=no_grad, ref=ref,
        )
        return fn

    return deco


def get_op_info(type: str) -> OpInfo:
    if type not in OPS:
        raise KeyError(f"no emitter registered for op type '{type}'")
    return OPS[type]


def has_op(type: str) -> bool:
    return type in OPS


def normalize_outs(outs) -> Dict[str, List[Any]]:
    """Emitters may return a single array, a dict of arrays, or a dict of
    lists; canonicalize to dict slot -> list."""
    if not isinstance(outs, dict):
        outs = {"Out": outs}
    norm = {}
    for slot, v in outs.items():
        if isinstance(v, (list, tuple)):
            norm[slot] = list(v)
        else:
            norm[slot] = [v]
    return norm


def _is_diff(x) -> bool:
    return x is not None and jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)


def run_forward(ctx: EmitCtx, op_type: str, ins, attrs) -> Dict[str, List[Any]]:
    info = get_op_info(op_type)
    return normalize_outs(info.forward(ctx, ins, attrs))


def exec_op_descs(ctx: EmitCtx, op_descs, env: Dict[str, Any],
                  skip_types=("feed", "fetch"), keep=frozenset()):
    """Trace a list of OpDescs into env — the executor's hot loop, also used
    by control-flow emitters on sub-blocks (the reference nests Executors,
    while_op.cc:35; here it's one trace). `keep` protects names (fetch
    targets) from delete_var."""
    for od in op_descs:
        if od.type in skip_types:
            continue
        if od.type == "delete_var":
            # memory_optimization_transpiler.release_memory marker: drop the
            # traced value so XLA's liveness ends here (reference
            # delete_var_op.cc frees the buffer). Fetch targets survive —
            # this executor injects fetches at run time, so program-level
            # liveness can't see them (unlike the reference's fetch ops).
            for n in od.input_names():
                if n not in keep:
                    env.pop(n, None)
            continue
        ins = {
            slot: [env.get(n) if n else None for n in names]
            for slot, names in od.inputs.items()
        }
        if od.type.endswith("_grad") and FWD_META_ATTR in od.attrs:
            outs = run_grad(ctx, ins, od.attrs)
        else:
            outs = run_forward(ctx, od.type, ins, od.attrs)
        for slot, names in od.outputs.items():
            vals = outs.get(slot, [])
            for i, n in enumerate(names):
                if n and i < len(vals) and vals[i] is not None:
                    env[n] = vals[i]


def run_grad(ctx: EmitCtx, ins: Dict[str, List[Any]], attrs: Dict[str, Any]):
    """Execute a generated `<type>_grad` op.

    Grad op IO convention (mirrors the reference's grad-op descs,
    grad_op_desc_maker.h):
      inputs:  fwd input slots as-is; fwd outputs under 'Out@<slot>';
               incoming output-gradients under 'GRAD@<out_slot>'
               (missing / '' entries mean "no gradient flows here")
      outputs: input-gradients under 'GRAD@<in_slot>'
    """
    meta = attrs[FWD_META_ATTR]
    info = get_op_info(meta["type"])
    fwd_attrs = dict(meta["attrs"])
    fwd_ins = {s: list(ins.get(s, [])) for s in meta["in_slots"]}

    if info.grad is not None:
        fwd_outs = {s: list(ins.get("Out@" + s, [])) for s in meta["out_slots"]}
        out_grads = {s: list(ins.get("GRAD@" + s, [])) for s in meta["out_slots"]}
        return normalize_outs(info.grad(ctx, fwd_ins, fwd_outs, out_grads, fwd_attrs))

    # generic path: vjp through the forward emitter w.r.t. inexact inputs
    diff_paths = [
        (s, i)
        for s, lst in fwd_ins.items()
        for i, x in enumerate(lst)
        if _is_diff(x) and s not in info.no_grad
    ]
    if not diff_paths:
        return {}

    def f(diff_vals):
        cur = {s: list(lst) for s, lst in fwd_ins.items()}
        for (s, i), v in zip(diff_paths, diff_vals):
            cur[s][i] = v
        return normalize_outs(info.forward(ctx, cur, fwd_attrs))

    primals = [fwd_ins[s][i] for s, i in diff_paths]
    out_primals, vjp_fn = jax.vjp(f, primals)

    cts = {}
    for s, lst in out_primals.items():
        gl = ins.get("GRAD@" + s, [])
        cts[s] = [
            gl[i]
            if i < len(gl) and gl[i] is not None
            else jnp.zeros_like(lst[i])
            for i in range(len(lst))
        ]
    (gins,) = vjp_fn(cts)

    result: Dict[str, List[Any]] = {}
    for s in fwd_ins:
        result["GRAD@" + s] = [None] * len(fwd_ins[s])
    for (s, i), g in zip(diff_paths, gins):
        result["GRAD@" + s][i] = g
    return result
