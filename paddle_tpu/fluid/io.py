"""Model persistence (reference python/paddle/fluid/io.py: save_vars:66,
save_params:132, save_persistables:145, load_*:158-234,
save_inference_model:298, load_inference_model:383).

Artifact layout matches the reference's contract: a `__model__` file holding
the serialized (pruned) ProgramDesc plus parameter payloads — here a single
`__params__.npz` (the save_combine path) or one .npy per var (save_vars
path). Checkpoints carry a crc32 in META (the Go pserver's checkpoint trick,
go/pserver/service.go:53)."""
from __future__ import annotations

import json
import os
import zlib
from typing import List, Optional

import numpy as np

from .executor import Executor, Scope, global_scope
from .framework import Parameter, Program, Variable, default_main_program

__all__ = [
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model", "get_inference_program",
    "save_checkpoint", "load_checkpoint", "latest_checkpoint_step",
    "export_compiled_model", "load_exported_model",
]

MODEL_FILENAME = "__model__"
PARAMS_FILENAME = "__params__.npz"


def _norm_npz(filename: str) -> str:
    # np.savez appends '.npz' when missing; normalize so load matches save
    return filename if filename.endswith(".npz") else filename + ".npz"


def _collect(program: Program, predicate) -> List[Variable]:
    return [v for v in program.list_vars() if predicate(v)]


def is_persistable(var: Variable) -> bool:
    return var.persistable


def is_parameter(var: Variable) -> bool:
    return isinstance(var, Parameter)


def _build_save_program(var_names, dirname, filename=None) -> Program:
    """Emit a program of save / save_combine ops (reference io.py:66,145:
    persistence IS a program — it can be serialized and shipped to another
    process, which is why save_op exists as an op and not a helper)."""
    prog = Program()
    block = prog.global_block()
    if filename is not None:
        for n in var_names:
            block.create_var(name=n, shape=None, persistable=True)
        block.append_op(
            "save_combine", inputs={"X": list(var_names)}, outputs={},
            attrs={"file_path": os.path.join(dirname, _norm_npz(filename))},
        )
    else:
        for n in var_names:
            block.create_var(name=n, shape=None, persistable=True)
            block.append_op(
                "save", inputs={"X": [n]}, outputs={},
                attrs={"file_path": os.path.join(
                    dirname, n.replace("/", "__"))},
            )
    return prog


def _build_load_program(var_names, dirname, filename=None) -> Program:
    """Emit the inverse load / load_combine program (reference
    load_combine_op.cc; load_persistables)."""
    prog = Program()
    block = prog.global_block()
    if filename is not None:
        for n in var_names:
            block.create_var(name=n, shape=None, persistable=True)
        block.append_op(
            "load_combine", inputs={}, outputs={"Out": list(var_names)},
            attrs={"file_path": os.path.join(dirname, _norm_npz(filename))},
        )
    else:
        for n in var_names:
            block.create_var(name=n, shape=None, persistable=True)
            block.append_op(
                "load", inputs={}, outputs={"Out": [n]},
                attrs={"file_path": os.path.join(
                    dirname, n.replace("/", "__") + ".npy")},
            )
    return prog


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None, scope: Optional[Scope] = None):
    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(main_program, predicate or is_persistable)
    os.makedirs(dirname, exist_ok=True)
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]
    prog = _build_save_program(names, dirname, filename)
    (executor or Executor()).run(prog, scope=scope)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope: Optional[Scope] = None, step: int = 0):
    """Persist every persistable var. With ``filename`` the legacy
    save_combine (.npz) path runs unchanged; WITHOUT one, the vars are
    written as a `paddle_tpu.checkpoint` manifest (ISSUE 12) — one
    writer discipline across the repo: per-tensor raw segments indexed
    by dtype/shape/offset/crc32, committed tmp+fsync+atomic-rename, so
    a training checkpoint gets the same torn-write safety and
    tensor-named corruption errors a serving checkpoint gets.
    ``load_persistables`` reads either form; `python -m
    paddle_tpu.checkpoint verify DIR` audits the manifest form."""
    if filename is not None:
        save_vars(executor, dirname, main_program, scope=scope,
                  predicate=is_persistable, filename=filename)
        return
    from ..checkpoint.format import save_checkpoint_tree

    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    arrays = {}
    for v in _collect(main_program, is_persistable):
        val = scope.find_var(v.name)
        if val is None:
            raise RuntimeError(
                f"persistable var '{v.name}' not initialized in scope — "
                "run the startup program before saving")
        arrays[v.name.replace("/", "__")] = np.asarray(val)
    save_checkpoint_tree(dirname, arrays,
                         meta={"kind": "persistables", "step": int(step)})


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None, scope: Optional[Scope] = None):
    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(main_program, predicate or is_persistable)
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]
    prog = _build_load_program(names, dirname, filename)
    (executor or Executor()).run(prog, scope=scope)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope: Optional[Scope] = None):
    """Inverse of save_persistables: reads the manifest form when the
    directory holds one (checksum-verified, zero-copy), else the
    legacy per-var/.npz op path."""
    import jax.numpy as jnp

    from ..checkpoint.format import MANIFEST_NAME, load_checkpoint_arrays

    if filename is None and \
            os.path.exists(os.path.join(dirname, MANIFEST_NAME)):
        main_program = main_program or default_main_program()
        scope = scope or global_scope()
        arrays, _manifest = load_checkpoint_arrays(dirname, verify=True)
        missing = sorted(
            v.name for v in _collect(main_program, is_persistable)
            if v.name.replace("/", "__") not in arrays)
        if missing:
            raise IOError(
                f"checkpoint manifest in '{dirname}' lacks persistable "
                f"var(s) {missing} that the program requires")
        for v in _collect(main_program, is_persistable):
            scope.set_var(v.name, jnp.asarray(
                np.asarray(arrays[v.name.replace('/', '__')])))
        return
    load_vars(executor, dirname, main_program, scope=scope,
              predicate=is_persistable, filename=filename)


def _prune_for_inference(program: Program, feeded_var_names, target_vars):
    """Backward-slice the global block to ops needed for the targets
    (reference Program.prune + inference_optimize)."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    needed = {v.name if isinstance(v, Variable) else str(v) for v in target_vars}
    keep = []
    for op in reversed(block.ops):
        if set(op.desc.output_names()) & needed:
            keep.append(op)
            needed.update(n for n in op.desc.input_names() if n)
    keep.reverse()
    block.ops = keep
    used = set()
    for op in keep:
        used.update(op.desc.input_names())
        used.update(op.desc.output_names())
    used.update(feeded_var_names)
    block.vars = {n: v for n, v in block.vars.items() if n in used}
    return pruned


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None):
    """reference io.py:298 — prune to feed/fetch targets, serialize program
    to `__model__`, save params."""
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    pruned = _prune_for_inference(main_program, feeded_var_names, target_vars)
    meta = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [
            v.name if isinstance(v, Variable) else str(v) for v in target_vars
        ],
    }
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME), "wb") as f:
        f.write(pruned.to_bytes())
    with open(os.path.join(dirname, "__meta__.json"), "w") as f:
        json.dump(meta, f)
    params = [v for v in pruned.list_vars() if isinstance(v, Parameter) or v.persistable]
    save_vars(None, dirname, main_program, vars=params,
              filename=params_filename or PARAMS_FILENAME)
    return meta["fetch_names"]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None,
                         scope: Optional[Scope] = None):
    """reference io.py:383 — returns (program, feed_names, fetch_vars).

    Serving turns this into a user-facing API (paddle_tpu/serving loads
    models by directory over RPC), so every missing artifact fails HERE
    with the offending path named — not as a bare FileNotFoundError /
    KeyError from deep inside `_build_load_program` or the load_combine
    host op. `scope` targets the load (default: the calling thread's
    global scope) so engines can populate private scopes without a
    scope_guard."""
    if not os.path.isdir(dirname):
        raise IOError(
            f"inference model directory '{dirname}' does not exist — "
            "pass the directory given to save_inference_model / "
            "export_compiled_model")
    model_path = os.path.join(dirname, model_filename or MODEL_FILENAME)
    if not os.path.exists(model_path):
        raise IOError(
            f"no serialized program at '{model_path}' — is '{dirname}' a "
            "save_inference_model directory? (export_compiled_model "
            "artifacts load via load_exported_model)")
    meta_path = os.path.join(dirname, "__meta__.json")
    if not os.path.exists(meta_path):
        raise IOError(
            f"missing feed/fetch metadata '{meta_path}' — the model "
            "directory is incomplete (was save_inference_model "
            "interrupted?)")
    with open(model_path, "rb") as f:
        program = Program.parse_from_bytes(f.read())
    with open(meta_path) as f:
        meta = json.load(f)
    persistables = [v for v in program.list_vars() if v.persistable]
    if persistables:
        params_path = os.path.join(
            dirname, _norm_npz(params_filename or PARAMS_FILENAME))
        if not os.path.exists(params_path):
            raise IOError(
                f"missing parameter payload '{params_path}' for model "
                f"directory '{dirname}'")
        with np.load(params_path) as payload:
            missing = sorted(v.name for v in persistables
                             if v.name not in payload.files)
        if missing:
            raise IOError(
                f"parameter payload '{params_path}' lacks persistable "
                f"var(s) {missing} that the program requires — the "
                "artifact was saved from a different program version")
        load_vars(executor, dirname, program, vars=persistables,
                  filename=params_filename or PARAMS_FILENAME, scope=scope)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


def get_inference_program(target_vars, main_program=None):
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    return _prune_for_inference(main_program, [], target_vars)


# --- compiled deploy artifact (role of the reference's C++ inference
#     library, paddle/fluid/inference/io.h:32 + paddle/capi: run a saved
#     model without the Python framework). The artifact is serialized
#     StableHLO (jax.export) with the parameters baked in as constants —
#     loadable by any PJRT runtime (C++/serving) or back into Python. ----
def export_compiled_model(dirname, feeded_var_names, target_vars,
                          executor=None, main_program=None,
                          scope: Optional[Scope] = None, batch_size: int = 1):
    """Prune to the inference slice, close over the current parameter
    values, and serialize the whole computation as StableHLO. Returns the
    artifact path."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    from .executor import _block_io, _lower

    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in target_vars]
    pruned = _prune_for_inference(main_program, feeded_var_names, target_vars)
    block = pruned.global_block()

    state_in, state_out = _block_io(block, set(feeded_var_names), scope)
    fn, ro_names, rw_names = _lower(
        block, tuple(feeded_var_names), tuple(fetch_names),
        tuple(state_in), tuple(state_out),
    )
    params = {}
    for n in state_in:
        val = scope.find_var(n)
        if val is None:
            raise RuntimeError(f"var '{n}' not initialized in scope")
        params[n] = jnp.asarray(val)

    def serve(*feed_arrays):
        feeds = dict(zip(feeded_var_names, feed_arrays))
        fetches, _ = fn(
            feeds,
            {n: params[n] for n in ro_names},
            {n: params[n] for n in rw_names},
            np.zeros((3,), np.uint32),
        )
        return tuple(fetches)

    specs = []
    feed_meta = []
    for n in feeded_var_names:
        var = block.var(n)
        shape = [batch_size if (d is None or d < 0) else int(d)
                 for d in var.shape]
        specs.append(jax.ShapeDtypeStruct(tuple(shape), np.dtype(str(var.dtype))))
        feed_meta.append({"name": n, "shape": shape, "dtype": str(var.dtype)})

    exported = jax_export.export(jax.jit(serve))(*specs)
    path = os.path.join(dirname, "__stablehlo__.bin")
    with open(path, "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(dirname, "__export_meta__.json"), "w") as f:
        json.dump({"feeds": feed_meta, "fetch_names": fetch_names}, f)
    return path


def load_exported_model(dirname):
    """Load a StableHLO artifact; returns (callable(*feeds) -> [fetches],
    feed_meta, fetch_names)."""
    from jax import export as jax_export

    with open(os.path.join(dirname, "__stablehlo__.bin"), "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(os.path.join(dirname, "__export_meta__.json")) as f:
        meta = json.load(f)

    def run(*feeds):
        return [np.asarray(x) for x in exported.call(*feeds)]

    return run, meta["feeds"], meta["fetch_names"]


# --- checkpoint/resume with integrity check (Go pserver capability,
#     go/pserver/service.go:119-227) ------------------------------------
def save_checkpoint(dirname, main_program=None, step: int = 0,
                    scope: Optional[Scope] = None, max_to_keep: int = 3):
    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    payload_path = os.path.join(dirname, f"ckpt_{step}.npz")
    vars_ = _collect(main_program, is_persistable)
    arrays = {}
    for v in vars_:
        val = scope.find_var(v.name)
        if val is not None:
            arrays[v.name] = np.asarray(val)
    np.savez(payload_path, **arrays)
    with open(payload_path, "rb") as f:
        crc = zlib.crc32(f.read())
    meta = {"step": step, "payload": os.path.basename(payload_path), "crc32": crc}
    tmp = os.path.join(dirname, "META.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(dirname, "META"))  # atomic, like the Go pserver
    # rotate: drop oldest payloads beyond max_to_keep, but never the one
    # META points to (a restart may save at a lower step than old files),
    # and ignore non-numeric ckpt_* names
    if max_to_keep > 0:
        def _step_of(f):
            try:
                return int(f[5:-4])
            except ValueError:
                return None

        current = os.path.basename(payload_path)
        ckpts = sorted(
            (f for f in os.listdir(dirname)
             if f.startswith("ckpt_") and f.endswith(".npz")
             and _step_of(f) is not None and f != current),
            key=_step_of,
        )
        for old in ckpts[:-(max_to_keep - 1) or len(ckpts)]:
            os.remove(os.path.join(dirname, old))
    return payload_path


def latest_checkpoint_step(dirname) -> Optional[int]:
    """Step of the checkpoint the directory holds, or None when it
    holds no (intact) one — the restart-time probe ElasticTrainer uses
    to decide between resume and fresh start without risking
    load_checkpoint's IOError on an empty dir. Recognizes BOTH forms:
    the legacy META (save_checkpoint) and a `paddle_tpu.checkpoint`
    manifest whose meta carries a step (save_persistables,
    save_decoder_checkpoint(step=))."""
    try:
        with open(os.path.join(dirname, "META")) as f:
            return int(json.load(f)["step"])
    except (OSError, ValueError, KeyError):
        pass
    try:
        from ..checkpoint.format import read_manifest

        step = (read_manifest(dirname).get("meta") or {}).get("step")
        return None if step is None else int(step)
    except (IOError, ValueError):
        return None


def load_checkpoint(dirname, main_program=None, scope: Optional[Scope] = None):
    import jax.numpy as jnp

    scope = scope or global_scope()
    with open(os.path.join(dirname, "META")) as f:
        meta = json.load(f)
    payload_path = os.path.join(dirname, meta["payload"])
    with open(payload_path, "rb") as f:
        data = f.read()
    if zlib.crc32(data) != meta["crc32"]:
        raise IOError(f"checkpoint {payload_path} is corrupt (crc mismatch)")
    payload = np.load(payload_path)
    for name in payload.files:
        scope.set_var(name, jnp.asarray(payload[name]))
    return meta["step"]
