"""Global runtime flags — the reference exposes gflags to Python
(reference python/paddle/fluid/__init__.py:121, framework/init.cc:31:
check_nan_inf, benchmark, fraction_of_gpu_memory_to_use, ...). Same shape
here, with TPU-relevant knobs."""
from __future__ import annotations

import os
from typing import Any, Dict

# the recorder parses PADDLE_TPU_TRACE / PADDLE_TPU_TRACE_BUFFER once at
# import; FLAGS reads its LIVE state rather than re-parsing the env, so
# one parser owns both views
from ..observability import tracing as _tracing


class _Flags(dict):
    """FLAGS with read-through keys: 'trace'/'trace_buffer' always report
    the live recorder (profiler() and trace_enable() toggle it without
    going through set_flags, so a stored mirror would go stale), and
    'faults' reports the live fault-injection plan the same way
    (faults.install()/scoped() toggle it without going through
    set_flags)."""

    def __getitem__(self, k):
        if k == "trace":
            return _tracing.trace_enabled()
        if k == "trace_buffer":
            return _tracing.buffer_capacity()
        if k == "faults":
            from ..distributed import faults as _faults

            return _faults.active_spec()
        return dict.__getitem__(self, k)


FLAGS: Dict[str, Any] = _Flags({
    # numeric precision of matmul/conv inside lowered blocks:
    #   'highest' = fp32 accumulate+multiply (reference fp32 CUDA parity)
    #   'high'    = bf16x3 on TPU
    #   'default' = bf16 multiply (fastest on MXU)
    "matmul_precision": "highest",
    # sweep outputs for NaN/Inf after each run (reference FLAGS_check_nan_inf,
    # executor.cc:27)
    "check_nan_inf": False,
    # log per-run timing (reference FLAGS_benchmark, executor.cc:348)
    "benchmark": False,
    # donate state buffers to jit for in-place HBM updates
    "donate_state": True,
    # hand-written Pallas kernels for hot ops: 'auto' = measured-winner
    # routing on TPU (flash attention at seq >= flash_min_seq, fused
    # layer_norm; NOT the fused conv, which loses to XLA on every
    # measured shape — see conv2d_bn_relu); True forces every kernel on
    # regardless of the measured tables (interpret-mode off-TPU, slow —
    # tests/A-B only; attention still honors flash_min_seq, so kernel
    # tests at short seq also set flash_min_seq 0); False = plain XLA
    "use_pallas_kernels": "auto",
    # minimum sequence length at which single-device attention routes to
    # the Pallas flash kernel instead of XLA's dense path. Measured on
    # TPU v5e (benchmarks/flash_attention_bench.py, slope-sync timing,
    # bf16 fwd+bwd): flash is 0.58x XLA at S=2048 but 1.85x at S=4096 —
    # XLA's dense attention wins while the S^2 score matrix still fits
    # comfortably in HBM bandwidth, flash wins once it doesn't. 0 = always
    # flash (and long-seq tests force it to exercise the kernel).
    "flash_min_seq": 3072,
    # cost-model-driven autotuning (ISSUE 8; paddle_tpu/autotune).
    # False = every knob is exactly its hand-set FLAGS default (zero
    # overhead, the pre-autotune behavior); True = routing thresholds
    # (flash_min_seq, paged_min_slots) and "auto" serving ladders read
    # through the tuning cache per DEVICE KIND (the FLAGS constants
    # demote to cold-cache defaults), and the executor logs per-shape
    # step timings into the cache
    "autotune": False,
    # where the tuning cache persists (tuning_cache.json, atomic
    # tmp-write+rename like master.snapshot). Seeded from
    # PADDLE_TPU_AUTOTUNE_DIR; '' = in-memory only. Read once, when the
    # process cache is first created (autotune.get_cache)
    "autotune_dir": os.environ.get("PADDLE_TPU_AUTOTUNE_DIR", ""),
    # minimum decode batch (slot count) at which paged attention routes
    # to the Pallas kernel instead of the pure-jax reference when
    # kernels are enabled. 1 = kernel always (the measured PR 6 answer
    # on v5e: decode attention is bandwidth-bound, the paged kernel
    # wins at every batch) — a cold-cache default the tuner overrides
    # per device kind (Ragged Paged Attention motivates per-chip
    # routing; a future chip's crossover need not be 1)
    "paged_min_slots": 1,
    # mixed precision: bf16 MXU operands with f32 accumulation for
    # conv/matmul (master weights and the rest of the graph stay f32) —
    # the standard TPU training configuration
    "amp": False,
    # tally while-loop step-fn evaluations via a host callback (tests use
    # it to pin the checkpointed while-grad at O(T) step evals)
    "count_while_step_evals": False,
    # escalate UNEXPECTED shape-inference failures (emitter bugs) from a
    # warn-once to a hard build-time error — the reference InferShape
    # enforce semantics (shape_inference.h). CI enables this; the warn
    # default keeps a conservative emitter from bricking user programs.
    "strict_shape_inference": False,
    # XLA cost accounting per compiled executable (ISSUE 3):
    #   'auto'/True = after each jit-cache miss, re-lower the program
    #                 (pure tracing, NO second XLA compile) and record
    #                 cost_analysis() flops/bytes into gauges + the
    #                 executor.compile_report() ring
    #   'full'      = additionally AOT-compile for memory_analysis()
    #                 (argument/temp/code bytes) — a REAL second XLA
    #                 compile per executable; benches opt in, training
    #                 loops shouldn't
    #   False       = off (no extra lowering at all)
    "compile_stats": "auto",
    # run the static program verifier (paddle_tpu.analysis.verify) before
    # lowering each new (program, feed signature) the executor compiles:
    # structural checks only (use-before-def, unknown vars/ops, block
    # nesting — not the abstract-eval shape re-check), so the cost is one
    # O(ops) walk per jit-cache MISS, never per step. Off by default for
    # users (the build-time inference already guards the common path);
    # tests/conftest.py turns it on suite-wide so every program any test
    # runs is verified.
    "verify_programs": False,
    # record host spans into paddle_tpu.observability.tracing from process
    # start (profiler()/trace_enable() also toggle at runtime). Purely a
    # host-side recorder: does NOT affect what gets traced/compiled, so
    # deliberately absent from trace_flags(). Reads are live (see _Flags);
    # the stored values here only seed `k in FLAGS` / sorted(FLAGS).
    "trace": _tracing.trace_enabled(),
    # span ring-buffer capacity (oldest spans drop past it)
    "trace_buffer": _tracing.buffer_capacity(),
    # deterministic fault-injection plan (distributed/faults.py spec
    # string, e.g. 'seed=7;drop@recv.push_grad:1,3'); None/'' = off.
    # Seeded from PADDLE_TPU_FAULTS; reads are live (see _Flags).
    "faults": None,
    # runtime sanitizers (ISSUE 7). 'guards' instruments the annotated
    # runtime classes (analysis/sanitize.py) so every access to a
    # '# guarded-by:'-declared attribute asserts its lock is held —
    # the dynamic validator of the static guards lint. Seeded from
    # PADDLE_TPU_SANITIZE at import; paddle_tpu/__init__ installs the
    # instrumentation at process start when set. '' = off.
    "sanitize": os.environ.get("PADDLE_TPU_SANITIZE", ""),
    # serving defaults (paddle_tpu/serving, ISSUE 5). The bucket ladder
    # is THE compile-bound knob: dynamic batches pad up to the next
    # ladder entry, so the executor jit cache holds at most one entry
    # per bucket per model version regardless of arrival pattern.
    "serving_buckets": "1,2,4,8,16",
    # admission bound: queue depth past which infer() is rejected with
    # ServerOverloaded instead of queueing into unbounded latency
    "serving_max_queue": 64,
    # batching timer: the oldest queued request waits at most this long
    # for batch-mates before its (possibly underfull) batch launches
    "serving_max_wait_ms": 5.0,
    # streaming generate (ISSUE 12): a token stream nobody polls for
    # this many seconds is presumed abandoned — the server cancels the
    # sequence (KV pages free immediately) and later continuations get
    # a typed StreamExpired. Generously past any sane client poll
    # cadence (frames block at most ~20s each by default)
    "serving_stream_ttl": 300.0,
    # decode serving (paddle_tpu/serving/decode.py, ISSUE 6). The slot
    # ladder is the decode analogue of serving_buckets: the fixed-slot
    # decode batch pads its slot count up to the next ladder entry, so
    # (together with the derived page-table-width ladder) the decode
    # step's jit cache is bounded at |slots| x |widths| shapes, all
    # pre-compiled at warm
    "decode_slots": "1,2,4",
    # KV page granularity in tokens. Smaller pages = less internal
    # fragmentation (reserve-at-admission rounds each sequence up to
    # whole pages) but wider page tables; 16 matches one v5e sublane
    # group of bf16 KV rows per head
    "kv_page_size": 16,
    # preallocated KV pool size in pages (page 0 is the reserved
    # garbage page): pages x page_size bounds decode HBM INDEPENDENT of
    # ragged sequence lengths — this is the decode admission bound
    "kv_num_pages": 128,
    # per-sequence cap on prompt + generated tokens; also sets the
    # page-table width ladder (ceil(max_seq_len / kv_page_size) is the
    # widest compiled table)
    "decode_max_seq_len": 128,
    # prefix caching (ISSUE 13): completed prompts publish their full
    # KV pages into a refcounted radix index; a request sharing a
    # cached prefix maps those pages read-only and prefills only its
    # suffix (steps-to-first-token drops to ceil(suffix/prefill_chunk))
    # with copy-on-write for the partial tail page. False = the PR 6
    # per-request-scratchpad pool, bit-identical
    "prefix_cache": True,
    # KV reservation policy (ISSUE 13): 'demand' reserves the prompt's
    # pages plus kv_decode_headroom pages at admission and grows
    # mid-decode — on exhaustion a victim spills to host and resumes
    # later (preempt-never-corrupts), so admitted concurrency is set by
    # ACTUAL token demand under long-tailed max_new_tokens;
    # 'worst_case' is the PR 6 ceil((prompt+max_new)/page_size)
    # reserve-at-admission policy (reserve-never-dies), kept as the
    # admitted-concurrency baseline
    "kv_reservation": "demand",
    # decode headroom (in pages) a demand-mode reservation adds past
    # the prompt, so the first generated tokens never immediately
    # trigger growth
    "kv_decode_headroom": 1,
    # where preempted sequences' KV pages spill ('' = host RAM; a
    # directory path = one .npz per preempted sequence, so heavy
    # preemption doesn't balloon the serving host's memory)
    "kv_spill_dir": "",
    # chunked prefill (ISSUE 10): per-step prompt-token budget AND the
    # compiled chunk width of the mixed decode step — a P-token prompt
    # completes prefill in ceil(P/prefill_chunk) steps instead of P.
    # 16 (= one kv_page_size of tokens per step) is the hand-set cold
    # default; the autotune cache overrides it per device kind
    # (DecodeEngine reads it through effective_flag; decode_bench's
    # measure-or-model session seeds measured values). 1 = chunking
    # off (bitwise the PR 6 one-token-per-step behavior)
    "prefill_chunk": 16,
    # speculative decoding (ISSUE 14): how many tokens the DRAFT
    # decoder proposes per live slot per scheduler round; the target
    # model then verifies all k+1 positions in ONE chunked step
    # (decoder_step_chunked rides the existing multi-token kernel), so
    # high draft/target agreement commits up to k+1 tokens per target
    # step. 0 = off (bit-identical non-speculative decode; engines
    # without a draft are always off regardless of this value). A PR 8
    # tunable: DecodeEngine reads it through effective_flag, so the
    # autotune cache overrides per device kind (decode_bench's
    # measure-or-model session persists the measured winner)
    "spec_k": 0,
    # SPMD mesh layer (paddle_tpu/mesh, ISSUE 15). Default TRAINING
    # mesh: a ParallelExecutor built without an explicit mesh= parses
    # this ("dp=2,tp=2,fsdp=2" — ordered named axes, sizes multiply to
    # the device count) and trains sharded; '' = the plain all-devices
    # dp mesh (bit-identical pre-mesh behavior). Pair with a
    # ShardingRules plan (mesh.transformer_rules gives the dp x tp x
    # fsdp layout for the flagship transformer)
    "mesh_axes": "",
    # default SERVING mesh for DecodeEngine/load_decoder: '' = single-
    # chip (the PR 6 engine); an axes string makes one decode replica
    # SPAN chips — params shard per mesh.decoder_rules and the paged KV
    # pool shards over the kv-head axis. A checkpoint that RECORDS a
    # mesh (save_decoder_checkpoint(mesh_axes=)) wins over this
    # default; an explicit load_decoder(mesh_axes=) wins over both
    "serving_mesh_axes": "",
    # serving fleet (paddle_tpu/fleet, ISSUE 11). Replica lease TTL in
    # seconds: a replica that misses heartbeats for this long is
    # evicted from the routing table (the pserver heartbeat/eviction
    # discipline applied to serving replicas; members beat at ttl/3)
    "fleet_lease_ttl": 5.0,
    # router-side load-report cache TTL in seconds: how stale a scraped
    # per-replica load report (free KV pages, queue depths) may be
    # before the next routing decision re-scrapes. Small = accurate
    # balancing, large = fewer load_report RPCs per routed request
    "fleet_scrape_ttl": 0.25,
    # autoscale policy loop (paddle_tpu/fleet/policy.py, ISSUE 17).
    # Evaluation cadence in seconds, and the hysteresis discipline:
    # a scale decision needs `fleet_policy_beats` CONSECUTIVE ticks of
    # the same verdict, and after any action the loop holds still for
    # `fleet_policy_cooldown` ticks (a spawning replica takes several
    # ticks to register — acting again before it lands would overshoot)
    "fleet_policy_interval": 0.5,
    "fleet_policy_beats": 3,
    "fleet_policy_cooldown": 8,
    # scale-UP floors: intent when fleet-wide free KV pages OR queue
    # headroom sits below these for `beats` consecutive ticks
    "fleet_free_page_floor": 8,
    "fleet_headroom_floor": 2,
    # scale-DOWN hysteresis margin: the fleet MINUS the drain victim
    # must retain margin x both scale-up floors — the dead band between
    # the up floor and the down bar is what keeps a boundary load from
    # flapping the fleet up and down forever
    "fleet_scale_margin": 2.0,
    # replica-count bounds the policy loop may never cross
    "fleet_min_replicas": 1,
    "fleet_max_replicas": 4,
    # replica-launcher crash-restart backoff base in seconds (doubles
    # per consecutive crash, capped launcher-side)
    "fleet_launcher_backoff": 0.25,
    # intent signing + deploy-path allowlist (fleet/auth.py). Key '' =
    # open mode (unsigned intents, bit-identical PR 11 behavior); the
    # PADDLE_TPU_FLEET_KEY env var wins over the flag so launcher-
    # spawned replica subprocesses inherit it. The allowlist is a
    # ':'-separated list of absolute dir prefixes every checkpoint_dir/
    # dirname/draft_checkpoint_dir payload path must resolve under
    # (PADDLE_TPU_FLEET_ALLOW env wins; '' = unrestricted)
    "fleet_intent_key": "",
    # previous fleet key, ACCEPTED (verify-only) during a key rotation
    # window (PADDLE_TPU_FLEET_KEY_PREV env wins). Producers always
    # sign with fleet_intent_key; set this to the old key on every
    # verifier before flipping producers, clear it when
    # fleet.auth.verified.prev_key stops moving. '' = no window
    "fleet_intent_key_prev": "",
    "fleet_intent_allowlist": "",
})


def pallas_enabled() -> bool:
    import jax

    v = FLAGS["use_pallas_kernels"]
    if v == "auto":
        return jax.default_backend() == "tpu"
    return bool(v)


def pallas_interpret() -> bool:
    """Off-TPU the kernels must run in interpreter mode."""
    import jax

    return jax.default_backend() != "tpu"


def set_flags(d: Dict[str, Any]):
    for k, v in d.items():
        if k not in FLAGS:
            raise KeyError(f"unknown flag {k!r}; known: {sorted(FLAGS)}")
        FLAGS[k] = v
        # propagate to the live recorder so set_flags is a complete
        # control surface. Each key acts independently: resizing the
        # buffer must not flip the enable bit (a profiler()-enabled
        # session stays enabled), and vice versa.
        if k == "trace":
            if v:
                _tracing.trace_enable(buffer_size=FLAGS["trace_buffer"])
            else:
                _tracing.trace_disable()
        elif k == "trace_buffer":
            _tracing.resize_buffer(int(v))
        elif k == "faults":
            from ..distributed import faults as _faults

            if v:
                _faults.install(v)
            else:
                _faults.uninstall()


def get_flag(name: str):
    return FLAGS[name]


def effective_flag(name: str, count: bool = True):
    """A routing knob's EFFECTIVE value: the FLAGS entry is the
    cold-cache default; with FLAGS['autotune'] on, a measured/derived/
    override record for this device kind in the tuning cache wins
    (each resolution counts autotune.cache.hits/misses — the evidence
    that routing reads THROUGH the cache; trace_flags passes
    count=False so per-step jit-key construction doesn't drown the
    handful of real route resolutions in thousands of increments).
    Off, this is exactly get_flag — zero overhead, bit-identical
    behavior."""
    base = FLAGS[name]
    if not FLAGS["autotune"]:
        return base
    from ..autotune import tuned_value

    return tuned_value(name, default=base, count=count)


def init_gflags(args=None):
    """reference core.init_gflags (pybind.cc:465) — accepts '--name=value'."""
    for a in args or []:
        a = a.lstrip("-")
        if "=" in a:
            k, v = a.split("=", 1)
            if v in ("true", "True"):
                v = True
            elif v in ("false", "False"):
                v = False
            set_flags({k: v})


def trace_flags() -> tuple:
    """Flags that change what gets TRACED (and therefore compiled): any
    executor jit-cache key must include them, or toggling a flag after the
    first run of a program would be silently ignored. Routing thresholds
    enter at their EFFECTIVE (tuner-resolved) value: a tuning-cache
    update changes the key, so stale executables compiled under the old
    threshold are never replayed for the new routing."""
    return (FLAGS["matmul_precision"], FLAGS["use_pallas_kernels"],
            FLAGS["amp"], FLAGS["count_while_step_evals"],
            effective_flag("flash_min_seq", count=False),
            effective_flag("paged_min_slots", count=False))
