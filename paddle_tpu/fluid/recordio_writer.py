"""Reader -> recordio file conversion (reference
python/paddle/fluid/recordio_writer.py:30 convert_reader_to_recordio_file).
Samples are pickled per record; files are written/read by the native
recordio library (csrc/recordio.cc) when available."""
from __future__ import annotations

import pickle
from typing import Callable, List

from ..native.recordio import DEFAULT_MAX_CHUNK, RecordIOWriter


def convert_reader_to_recordio_file(
    filename: str, reader_creator: Callable,
    max_chunk_bytes: int = DEFAULT_MAX_CHUNK,
) -> int:
    """Write every sample of the reader into one recordio file; returns the
    record count."""
    w = RecordIOWriter(filename, max_chunk_bytes)
    n = 0
    try:
        for sample in reader_creator():
            w.write(pickle.dumps(sample, protocol=pickle.HIGHEST_PROTOCOL))
            n += 1
    finally:
        w.close()
    return n


def convert_reader_to_recordio_files(
    filename_prefix: str, batch_per_file: int, reader_creator: Callable,
    max_chunk_bytes: int = DEFAULT_MAX_CHUNK,
) -> List[str]:
    """Shard the reader's samples across several files
    (`<prefix>-00000`, ...) — the unit the elastic master service hands out
    as tasks (reference go/master dataset sharding)."""
    files: List[str] = []
    w = None
    n_in_file = 0
    try:
        for i, sample in enumerate(reader_creator()):
            if w is None or n_in_file >= batch_per_file:
                if w is not None:
                    w.close()
                path = f"{filename_prefix}-{len(files):05d}"
                files.append(path)
                w = RecordIOWriter(path, max_chunk_bytes)
                n_in_file = 0
            w.write(pickle.dumps(sample, protocol=pickle.HIGHEST_PROTOCOL))
            n_in_file += 1
    finally:
        if w is not None:
            w.close()
    return files
