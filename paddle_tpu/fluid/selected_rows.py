"""SelectedRows — sparse row-gradient representation, TPU-native.

Capability-parity with the reference's `paddle/fluid/framework/selected_rows.h`
(row-index list + value tensor) and the sparse functor library
(`paddle/fluid/operators/math/selected_rows_functor.cc`), re-expressed as a
JAX pytree with STATIC shapes so it can flow through jit/vjp/SPMD:

  - `rows` is int32 [N] (N = number of lookups in the batch, duplicates
    allowed — the reference's un-merged SelectedRows), `value` is [N, ...].
  - `height` (the dense dim-0 extent, i.e. vocab size) is static aux data.
  - Optimizers apply updates row-wise without ever materializing the dense
    [height, ...] gradient (reference sparse sgd/adam kernels,
    `operators/sgd_op.h`, `operators/adam_op.h` SparseAdamFunctor).
  - Duplicate-row merging (reference `MergeAdd`) keeps static shape: rows are
    sorted, each unique row's sum lands at its first occurrence, and a 0/1
    mask marks the merged entries; scatter applies of masked deltas are then
    duplicate-safe.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    """rows: int [N]; value: [N, d1, ...]; height: static int (dense dim 0)."""

    def __init__(self, rows, value, height: int):
        self.rows = rows
        self.value = value
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.value), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, value = children
        return cls(rows, value, height)

    @property
    def dense_shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    @property
    def dtype(self):
        return self.value.dtype

    def astype(self, dtype):
        return SelectedRows(self.rows, self.value.astype(dtype), self.height)

    def to_dense(self):
        """Scatter-add into the dense shape (reference
        SelectedRows::Get / sum_op's selected-rows branch). O(height) memory —
        only for fallback paths and tests."""
        z = jnp.zeros(self.dense_shape, self.value.dtype)
        return z.at[self.rows].add(self.value)

    def merged(self):
        """Duplicate-row merge with static shapes (reference MergeAdd,
        selected_rows_functor.cc).

        Returns (rows_sorted, merged_value, first_mask):
          - rows_sorted: rows ascending, [N]
          - merged_value[i] = sum of value over all duplicates of
            rows_sorted[i] if i is the first occurrence, else 0
          - first_mask: float 0/1 [N], 1 at first occurrences

        A scatter of `first_mask * delta` at `rows_sorted` is then exact and
        duplicate-safe (the 0-masked entries contribute nothing).
        """
        rows = self.rows.reshape(-1)
        n = rows.shape[0]
        order = jnp.argsort(rows)
        r_s = rows[order]
        v_s = self.value[order]
        first = jnp.concatenate(
            [jnp.ones((1,), bool), r_s[1:] != r_s[:-1]])
        seg = jnp.cumsum(first) - 1  # unique-row segment id per element
        summed = jax.ops.segment_sum(v_s, seg, num_segments=n)
        bshape = (n,) + (1,) * (self.value.ndim - 1)
        merged = jnp.where(first.reshape(bshape), summed[seg], 0)
        return r_s, merged, first.astype(self.value.dtype)


def is_selected_rows(x) -> bool:
    return isinstance(x, SelectedRows)


def add_any(a, b):
    """dense+dense, sparse+sparse (concat — stays sparse, reference sum_op
    keeps SelectedRows when all inputs are), or mixed (densifies)."""
    if is_selected_rows(a) and is_selected_rows(b):
        assert a.height == b.height, (a.height, b.height)
        return SelectedRows(
            jnp.concatenate([a.rows.reshape(-1), b.rows.reshape(-1)]),
            jnp.concatenate([a.value, b.value]),
            a.height,
        )
    if is_selected_rows(a):
        return b.at[a.rows].add(a.value)
    if is_selected_rows(b):
        return a.at[b.rows].add(b.value)
    return a + b
