"""Guarded-by inference and shared-state race lint ("TSan-lite").

The PR 4 lock lint (analysis/locks.py) reasons about lock *acquisition
order*; it cannot see which state each lock protects — the bug class
that actually dominated the serving/decode reviews (stop-races-step
double answers, gauge clobbering, check-then-act windows). This pass is
the Python analogue of Clang's ``-Wthread-safety`` / ``GUARDED_BY``:
it infers, per class (and per module for module-level state), the lock
that guards each piece of shared mutable state, then proves every
access on a multi-thread-reachable path holds it.

Pipeline (extending the locks.py AST machinery):

  1. **Thread entries.** A method whose VALUE escapes — passed to
     ``threading.Thread(target=self._loop)``, registered in an
     ``RpcServer({...})`` / handler dict, handed to
     ``atexit.register`` — runs on another thread. Together with the
     public surface (called by arbitrary client threads) they root the
     same-class call-graph closure of multi-thread-reachable methods.
     ``__init__`` (and anything reachable only from it, or only from
     module import time) is exempt: construction is single-threaded.

  2. **Guard inference.** A ``self._x`` attribute is *shared mutable*
     if it is written outside ``__init__``; its guard is either
     declared — a ``# guarded-by: _mu`` comment on the ``__init__``
     assignment — or inferred as the lock held at the (strict)
     majority of its accesses, when one lock covers every
     locked access. Interprocedural: a ``*_locked`` helper called only
     under a lock analyzes with that lock held (intersection over its
     same-scope call sites, ``__init__`` call sites excluded).

  3. **Reports** (all errors):

     L104  shared attribute accessed without its declared/inferred
           guard on a multi-thread-reachable path
     L105  attribute guarded by *different* locks at different sites
           (no single lock covers the locked accesses)
     L106  check-then-act: a guarded read, the lock released, and a
           later re-acquisition writing the same attribute in the same
           function — the PR 5/6 double-answer shape

Suppressions (reviewable, at the site)::

    # guarded-by: _mu                 declare the guard (on the
                                      __init__ assignment; also drives
                                      the runtime sanitizer,
                                      PADDLE_TPU_SANITIZE=guards)
    # lint: allow-unguarded(_x)       vet one attribute's lock-free
                                      access on this line (or the whole
                                      function from its def line)
    # lint: allow-unguarded           same, any attribute on the line

The runtime half lives in analysis/sanitize.py: under
``PADDLE_TPU_SANITIZE=guards`` the declared guards are asserted held at
every attribute access, turning the tier-1 concurrency tests into
dynamic validators of this static model.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .diagnostics import ERROR, Diagnostic
from .locks import _LOCK_CTORS, _contains_lock_ctor, _expr_text

PASS_NAME = "guards"

# method names that mutate their receiver (container writes through a
# read of the attribute binding)
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse", "move_to_end",
}

# module-level containers these ctors build count as module state
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "OrderedDict",
                    "defaultdict", "Counter"}

# the declaration may ride a comment with leading prose
# ("# rid -> state; guarded-by: _mu"), not only start it
_DECL_RE = re.compile(r"#.*?\bguarded-by:\s*([A-Za-z_]\w*)")
_ALLOW_RE = re.compile(r"#\s*lint:\s*allow-unguarded(?:\(([^)]*)\))?")
_ALIAS_RE = re.compile(r"#\s*lint:\s*lock-alias\b")


def _d(code, msg, where, hint=""):
    return Diagnostic(code=code, severity=ERROR, message=msg, where=where,
                      hint=hint, pass_name=PASS_NAME)


class _Directives:
    """Per-line guarded-by declarations and allow-unguarded vets."""

    def __init__(self, src: str):
        self.decl_by_line: Dict[int, str] = {}
        # line -> set of vetted attr names ('*' = any attr on the line)
        self.allow_by_line: Dict[int, Set[str]] = {}
        # lines carrying '# lint: lock-alias' — the assigned attribute
        # IS a lock, injected by the owner (see locks.py's catalog)
        self.lock_alias_lines: Set[int] = set()
        lines = src.splitlines()
        for i, line in enumerate(lines, start=1):
            if _ALIAS_RE.search(line):
                self.lock_alias_lines.add(i)
            m = _DECL_RE.search(line)
            if m:
                self.decl_by_line[i] = m.group(1)
            m = _ALLOW_RE.search(line)
            if m:
                attrs = {a.strip() for a in (m.group(1) or "").split(",")
                         if a.strip()} or {"*"}
                self.allow_by_line.setdefault(i, set()).update(attrs)
                # a directive inside a standalone comment block also
                # covers the next code line below it (same rule as the
                # locks lint), so a vet can sit above its def/statement
                if line.lstrip().startswith("#"):
                    j = i
                    while j < len(lines) and (
                            not lines[j].strip()
                            or lines[j].lstrip().startswith("#")):
                        j += 1
                    if j < len(lines):
                        self.allow_by_line.setdefault(
                            j + 1, set()).update(attrs)

    def allows(self, attr: str, *lines: int) -> bool:
        for ln in lines:
            if not ln:
                continue
            vetted = self.allow_by_line.get(ln)
            if vetted and ("*" in vetted or attr in vetted):
                return True
        return False

    def decl_for(self, node) -> Optional[str]:
        """The guarded-by declaration riding a (possibly multi-line)
        assignment statement — the comment may sit on a continuation
        line."""
        for ln in range(node.lineno,
                        (getattr(node, "end_lineno", None) or
                         node.lineno) + 1):
            if ln in self.decl_by_line:
                return self.decl_by_line[ln]
        return None


class _Access:
    __slots__ = ("attr", "line", "write", "held", "fn", "with_line")

    def __init__(self, attr, line, write, held, fn, with_line):
        self.attr = attr            # '_queue' / module var name
        self.line = line
        self.write = write
        self.held: FrozenSet[str] = held   # lock ids held LOCALLY
        self.fn = fn                # owning function name
        self.with_line = with_line  # innermost with line (or 0)


class _Fn:
    __slots__ = ("name", "node", "accesses", "calls", "base", "regions")

    def __init__(self, name, node):
        self.name = name
        self.node = node
        self.accesses: List[_Access] = []
        # (callee, frozenset(held), in_init)
        self.calls: List[Tuple[str, FrozenSet[str], bool]] = []
        self.base: Optional[FrozenSet[str]] = None  # caller-held locks
        # L106 regions: (lock_id, with_line, reads, writes) in order
        self.regions: List[Tuple[str, int, Set[str], Set[str]]] = []


class _Scope:
    """One lint scope: a module's top level, or one class."""

    def __init__(self, qual: str, is_class: bool):
        self.qual = qual
        self.is_class = is_class
        self.locks: Dict[str, str] = {}      # expr text -> canonical id
        self.lock_attrs: Set[str] = set()    # short attr/var names of locks
        self.fns: Dict[str, _Fn] = {}
        self.state: Set[str] = set()         # tracked attr/var names
        self.written: Set[str] = set()       # written outside __init__
        self.decls: Dict[str, str] = {}      # attr -> declared lock id
        self.entries: Set[str] = set()       # thread-entry methods
        self.multi: Set[str] = set()         # multi-thread-reachable fns


def _collect_locks(scope: _Scope, body, self_name: str,
                   directives=None):
    """Lock-attribute discovery, mirroring locks.py (Condition(self._mu)
    aliases the wrapped lock; dict-of-locks families get an '[]' id;
    `# lint: lock-alias` marks an injected shared lock — see
    locks.py's directive catalog)."""
    for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = _expr_text(node.targets[0])
        if tgt is None:
            continue
        own = tgt.startswith(self_name + ".") if self_name != "<module>" \
            else "." not in tgt
        val = node.value
        if own and directives is not None and \
                node.lineno in directives.lock_alias_lines:
            short = tgt.split(".")[-1]
            scope.locks[tgt] = f"{scope.qual}.{short}"
            scope.lock_attrs.add(short)
            continue
        if isinstance(val, ast.Call):
            fn = val.func
            ctor = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if ctor in _LOCK_CTORS and own:
                short = tgt.split(".")[-1]
                scope.locks[tgt] = f"{scope.qual}.{short}"
                scope.lock_attrs.add(short)
                continue
            if ctor == "Condition" and own:
                alias = None
                if val.args:
                    alias = scope.locks.get(_expr_text(val.args[0]) or "")
                short = tgt.split(".")[-1]
                scope.locks[tgt] = alias or f"{scope.qual}.{short}"
                scope.lock_attrs.add(short)
                continue
        if _contains_lock_ctor(val) and not isinstance(val, ast.Call) \
                and own:
            short = tgt.split(".")[-1]
            scope.locks[tgt + "[]"] = f"{scope.qual}.{short}[]"
            scope.lock_attrs.add(short)


def _walk_own_stmts(stmts):
    """Statements of a body without descending into nested defs."""
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        yield st


class _Lint:
    def __init__(self, filename: str, src: str):
        self.filename = filename
        self.short = os.path.splitext(os.path.basename(filename))[0]
        self.src = src
        self.directives = _Directives(src)
        self.diags: List[Diagnostic] = []

    def where(self, line: int) -> str:
        return f"{self.filename}:{line}"

    # --- scope construction ----------------------------------------------
    def _class_scope(self, cls: ast.ClassDef, mod: _Scope) -> _Scope:
        scope = _Scope(f"{self.short}.{cls.name}", is_class=True)
        scope.locks.update(mod.locks)     # module locks visible
        scope.lock_attrs |= mod.lock_attrs
        _collect_locks(scope, cls.body, self_name="self",
                       directives=self.directives)
        for n in cls.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.fns[n.name] = _Fn(n.name, n)
        self._find_state_and_decls(scope)
        self._find_entries(scope)
        return scope

    def _module_scope(self, tree: ast.Module) -> _Scope:
        scope = _Scope(self.short, is_class=False)
        _collect_locks(scope, tree.body, self_name="<module>",
                       directives=self.directives)
        for n in tree.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.fns[n.name] = _Fn(n.name, n)
        # module state: top-level container assignments + globals
        # rebound from functions
        for n in tree.body:
            targets, val = [], None
            if isinstance(n, ast.Assign):
                targets, val = n.targets, n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                targets, val = [n.target], n.value
            for t in targets:
                if not isinstance(t, ast.Name) or \
                        t.id in scope.lock_attrs or t.id == "__all__":
                    continue
                is_container = isinstance(
                    val, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp))
                if isinstance(val, ast.Call):
                    fn = val.func
                    ctor = fn.attr if isinstance(fn, ast.Attribute) else (
                        fn.id if isinstance(fn, ast.Name) else None)
                    is_container = ctor in _CONTAINER_CTORS
                if is_container:
                    scope.state.add(t.id)
                    g = self.directives.decl_for(n)
                    if g:
                        gid = scope.locks.get(g)
                        if gid:
                            scope.decls[t.id] = gid
                        else:
                            # same contract as the class-scope path: a
                            # typo'd/renamed lock must not silently
                            # disable checking for this global
                            self.diags.append(_d(
                                "L105",
                                f"'# guarded-by: {g}' on '{t.id}' names "
                                f"no known module-level lock of "
                                f"{scope.qual}",
                                self.where(n.lineno),
                                hint="declare the guard with the lock's "
                                     "module-level name, e.g. "
                                     "'# guarded-by: _mu'"))
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                for name in node.names:
                    if name not in scope.lock_attrs:
                        scope.state.add(name)
        return scope

    def _find_state_and_decls(self, scope: _Scope):
        """Shared-mutable attrs: written outside __init__ anywhere in the
        class; declarations ride __init__ assignment lines."""
        init = scope.fns.get("__init__")
        if init is not None:
            for st in ast.walk(init.node):
                tgts = []
                if isinstance(st, ast.Assign):
                    tgts = st.targets
                elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
                    tgts = [st.target]
                for t in tgts:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self" and \
                            t.attr not in scope.lock_attrs:
                        decl = self.directives.decl_for(st)
                        if decl:
                            # the guard may be a class attr ('self._mu')
                            # or a visible module-level lock — both are
                            # legal held-set members, so both declare
                            gid = scope.locks.get("self." + decl) or \
                                scope.locks.get(decl)
                            if gid is None:
                                self.diags.append(_d(
                                    "L105",
                                    f"'# guarded-by: {decl}' on "
                                    f"'self.{t.attr}' names no known lock "
                                    f"attribute of {scope.qual}",
                                    self.where(st.lineno),
                                    hint="declare the guard with the "
                                         "lock's attribute name, e.g. "
                                         "'# guarded-by: _mu'"))
                            else:
                                scope.decls[t.attr] = gid
                                scope.state.add(t.attr)

    def _find_entries(self, scope: _Scope):
        """Functions/methods whose VALUE escapes (Thread targets, RPC
        handler dicts, atexit hooks) run on other threads. Class scope
        matches escaped `self.method` attributes; module scope matches
        escaped bare function names."""
        fn_names = set(scope.fns)

        def escaped_name(node):
            if scope.is_class:
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self" and \
                        node.attr in fn_names:
                    return node.attr
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in fn_names:
                return node.id
            return None

        for fn in scope.fns.values():
            call_funcs = set()
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    call_funcs.add(id(node.func))
            for node in ast.walk(fn.node):
                name = escaped_name(node)
                if name is not None and id(node) not in call_funcs:
                    scope.entries.add(name)

    # --- symbolic walk ----------------------------------------------------
    def _resolve_lock(self, scope: _Scope, node) -> Optional[str]:
        txt = _expr_text(node)
        return scope.locks.get(txt) if txt else None

    def _state_name(self, scope: _Scope, node) -> Optional[str]:
        """The tracked attr/var a node refers to, or None."""
        if scope.is_class:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and \
                    node.attr not in scope.lock_attrs:
                return node.attr
        else:
            if isinstance(node, ast.Name) and \
                    node.id not in scope.lock_attrs:
                return node.id
        return None

    def _scan_stmt_exprs(self, scope, fn, st, held, with_line,
                         region):
        """Record accesses in one statement's own expressions."""
        consumed: Set[int] = set()
        writes: List[Tuple[str, int]] = []
        reads: List[Tuple[str, int]] = []

        def mark(node):
            for sub in ast.walk(node):
                consumed.add(id(sub))

        for node in ast.walk(st):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                mark(node)
        for node in ast.walk(st):
            if id(node) in consumed:
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                name = self._state_name(scope, node.func.value)
                if name is not None:
                    writes.append((name, node.lineno))
                    mark(node.func)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                name = self._state_name(scope, node.value)
                if name is not None:
                    writes.append((name, node.lineno))
                    mark(node.value)
        for node in ast.walk(st):
            if id(node) in consumed:
                continue
            name = self._state_name(scope, node)
            if name is None:
                continue
            mark(node)
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                writes.append((name, node.lineno))
            else:
                reads.append((name, node.lineno))
            if isinstance(st, ast.AugAssign) and \
                    st.target is node:  # x += 1 reads AND writes
                reads.append((name, node.lineno))

        in_init = scope.is_class and fn.name == "__init__"
        for name, line, write in (
                [(n, ln, True) for n, ln in writes] +
                [(n, ln, False) for n, ln in reads]):
            if not in_init:
                fn.accesses.append(_Access(name, line, write, held,
                                           fn.name, with_line))
                if write:
                    scope.written.add(name)
            if region is not None:
                (region[3] if write else region[2]).add(name)

        # same-scope calls
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if scope.is_class:
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self":
                    callee = f.attr
            else:
                if isinstance(node.func, ast.Name):
                    callee = node.func.id
            if callee and callee in scope.fns:
                fn.calls.append((callee, held, in_init))

    def _walk_fn(self, scope: _Scope, fn: _Fn):
        def visit(stmts, held: FrozenSet[str], with_line: int,
                  region):
            for st in _walk_own_stmts(stmts):
                if isinstance(st, ast.With):
                    new_held = set(held)
                    lock_id = None
                    for item in st.items:
                        self._scan_stmt_exprs(scope, fn, item.context_expr,
                                              held, with_line, region)
                        cid = self._resolve_lock(scope, item.context_expr)
                        if cid:
                            new_held.add(cid)
                            lock_id = cid
                    sub_region = region
                    if lock_id is not None and region is None:
                        sub_region = (lock_id, st.lineno, set(), set())
                        fn.regions.append(sub_region)
                    visit(st.body, frozenset(new_held), st.lineno,
                          sub_region)
                    continue
                # the statement's own expressions (incl. if/while tests,
                # for iters, call args) — scan a shallow copy without
                # nested statement lists so lines aren't double-counted
                shallow = st
                nested = []
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if isinstance(sub, list) and sub and \
                            isinstance(sub[0], ast.stmt):
                        nested.append((field, sub))
                handlers = getattr(st, "handlers", [])
                if nested or handlers:
                    shallow = type(st).__new__(type(st))
                    for field, value in ast.iter_fields(st):
                        if field in ("body", "orelse", "finalbody",
                                     "handlers") and isinstance(value, list):
                            setattr(shallow, field, [])
                        else:
                            setattr(shallow, field, value)
                self._scan_stmt_exprs(scope, fn, shallow, held, with_line,
                                      region)
                for _field, sub in nested:
                    visit(sub, held, with_line, region)
                for h in handlers:
                    visit(h.body, held, with_line, region)

        visit(fn.node.body, frozenset(), 0, None)

    # --- interprocedural base sets ---------------------------------------
    def _compute_bases(self, scope: _Scope, roots: Set[str]):
        """base[fn] = locks held at EVERY non-__init__ call site (so a
        *_locked helper analyzes under its callers' lock); roots
        (public surface, thread entries) are callable bare."""
        callers: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for fn in scope.fns.values():
            for callee, held, in_init in fn.calls:
                if in_init:
                    continue
                callers.setdefault(callee, []).append((fn.name, held))
        for name, fn in scope.fns.items():
            fn.base = frozenset() if name in roots else None
        for _ in range(len(scope.fns) + 1):
            changed = False
            for name, fn in scope.fns.items():
                if name in roots:
                    continue
                sets = []
                for caller, held in callers.get(name, ()):
                    cb = scope.fns[caller].base
                    if cb is None:
                        continue
                    sets.append(frozenset(cb | held))
                new = (frozenset(sets[0]).intersection(*sets[1:])
                       if sets else None)
                if new != fn.base and new is not None:
                    fn.base = new
                    changed = True
            if not changed:
                break

    def _reachable(self, scope: _Scope, roots: Set[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in scope.fns]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            for callee, _held, in_init in scope.fns[name].calls:
                if callee in scope.fns and not in_init:
                    stack.append(callee)
        return seen

    # --- checks -----------------------------------------------------------
    def _check_scope(self, scope: _Scope):
        if not scope.locks:
            return
        # `*_locked` methods are never roots — the repo convention says
        # their callers hold the lock, so they analyze under the
        # intersection of their call sites' held sets
        if scope.is_class:
            public = {n for n in scope.fns
                      if not n.startswith("_") or
                      (n.startswith("__") and n.endswith("__")
                       and n != "__init__")}
            roots = (public | scope.entries) - \
                {n for n in scope.fns if n.endswith("_locked")}
            if not roots:
                return
        else:
            public = {n for n in scope.fns if not n.startswith("_")}
            roots = (public | scope.entries) - \
                {n for n in scope.fns if n.endswith("_locked")}
        for fn in scope.fns.values():
            self._walk_fn(scope, fn)
        scope.multi = self._reachable(scope, roots)
        if scope.is_class:
            scope.multi.discard("__init__")
        self._compute_bases(scope, roots)

        # class scope: any self-attr written outside __init__ (or
        # declared); module scope: additionally restricted to the
        # module-state vars (container globals / `global`-rebound) so
        # plain locals never enter the analysis
        tracked = (scope.written | set(scope.decls)) - scope.lock_attrs
        if not scope.is_class:
            tracked &= scope.state | set(scope.decls)

        # collect effective accesses per attr (reachable fns only)
        per_attr: Dict[str, List[Tuple[_Access, FrozenSet[str]]]] = {}
        for fn in scope.fns.values():
            if fn.name not in scope.multi:
                continue
            base = fn.base or frozenset()
            for a in fn.accesses:
                if a.attr in tracked:
                    per_attr.setdefault(a.attr, []).append(
                        (a, frozenset(a.held | base)))

        prefix = "self." if scope.is_class else ""
        for attr in sorted(per_attr):
            accesses = per_attr[attr]
            locked = [(a, h) for a, h in accesses if h]
            declared = scope.decls.get(attr)
            guard = declared
            if guard is None:
                if len(locked) < 2 or len(locked) * 2 <= len(accesses):
                    continue  # no usable inference
                common = frozenset(locked[0][1]).intersection(
                    *[h for _, h in locked[1:]])
                if not common:
                    self._report_l105(scope, attr, prefix, locked)
                    continue
                guard = sorted(common)[0]
            fn_lines = {f.name: f.node.lineno for f in scope.fns.values()}
            for a, held in accesses:
                if guard in held:
                    continue
                if self.directives.allows(attr, a.line, a.with_line,
                                          fn_lines.get(a.fn, 0)):
                    continue
                kind = "written" if a.write else "read"
                self.diags.append(_d(
                    "L104",
                    f"shared attribute '{prefix}{attr}' (guarded by "
                    f"'{_short(guard)}') is {kind} without its guard in "
                    f"{scope.qual}.{a.fn}() on a multi-thread path",
                    self.where(a.line),
                    hint=f"hold '{_short(guard)}' across this access, or "
                         f"annotate '# lint: allow-unguarded({attr})' "
                         "with a rationale if the lock-free access is "
                         "deliberate"))
            self._check_l106(scope, attr, guard, prefix)

    def _report_l105(self, scope, attr, prefix, locked):
        lock_names = sorted({_short(l) for _, hs in locked for l in hs})
        fn_lines = {f.name: f.node.lineno for f in scope.fns.values()}
        sites = sorted({a.line for a, _ in locked})
        if any(self.directives.allows(attr, a.line, a.with_line,
                                      fn_lines.get(a.fn, 0))
               for a, _ in locked):
            return
        self.diags.append(_d(
            "L105",
            f"shared attribute '{prefix}{attr}' is guarded by DIFFERENT "
            f"locks at different sites ({', '.join(lock_names)}; lines "
            f"{', '.join(str(s) for s in sites[:4])}) — no single lock "
            "covers it",
            self.where(sites[0]),
            hint="pick one guard for the attribute (declare it with "
                 "'# guarded-by: <lock>') and take that lock at every "
                 "site"))

    def _check_l106(self, scope: _Scope, attr: str, guard: str,
                    prefix: str):
        """Read-under-guard, release, later re-acquire + write, in one
        function — the check-then-act shape."""
        fn_lines = {f.name: f.node.lineno for f in scope.fns.values()}
        for fn in scope.fns.values():
            if fn.name not in scope.multi:
                continue
            base = fn.base or frozenset()
            if guard in base:
                continue  # never released between the regions
            regions = [r for r in fn.regions if r[0] == guard]
            for i, (lock, line_r, reads, _w) in enumerate(regions):
                if attr not in reads:
                    continue
                for (_lock2, line_w, _r2, writes) in regions[i + 1:]:
                    if attr not in writes:
                        continue
                    if self.directives.allows(
                            attr, line_r, line_w,
                            fn_lines.get(fn.name, 0)):
                        continue
                    self.diags.append(_d(
                        "L106",
                        f"check-then-act on '{prefix}{attr}' in "
                        f"{scope.qual}.{fn.name}(): read under "
                        f"'{_short(guard)}' at line {line_r}, lock "
                        f"released, dependent write re-acquires it at "
                        f"line {line_w}",
                        self.where(line_w),
                        hint="merge the two critical sections (the "
                             "stop-races-step double-answer shape from "
                             "the serving reviews), or re-validate the "
                             "read inside the second acquisition and "
                             "annotate '# lint: allow-unguarded"
                             f"({attr})'"))
                    break

    # --- entry ------------------------------------------------------------
    def run(self):
        try:
            tree = ast.parse(self.src, filename=self.filename)
        except SyntaxError as e:
            self.diags.append(_d("L104", f"unparseable source: {e}",
                                 self.where(getattr(e, "lineno", 0) or 0)))
            return
        mod = self._module_scope(tree)
        self._find_entries(mod)
        # module-global accesses come from top-level functions AND class
        # methods (e.g. the trace ring's Span.__exit__ appends) — keyed
        # qualified ('Cls.meth'), so a method sharing a module
        # function's bare name still gets analyzed
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for n in node.body:
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        mod.fns[f"{node.name}.{n.name}"] = _Fn(
                            f"{node.name}.{n.name}", n)
        # class-method "functions" in the module scope are reachable
        # from wherever their class is used: treat all non-__init__
        # ones as roots alongside the module's own public surface
        extra_roots = {n for n in mod.fns if "." in n
                       and not n.endswith(".__init__")}
        mod.entries |= extra_roots
        self._check_scope(mod)

        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            scope = self._class_scope(cls, mod)
            self._check_scope(scope)


def _short(lock_id: str) -> str:
    return lock_id.rsplit(".", 1)[-1]


def lint_source(src: str, filename: str = "<src>") -> List[Diagnostic]:
    """Lint one source string (unit tests / selftest)."""
    lint = _Lint(filename, src)
    lint.run()
    return lint.diags


def lint_paths(paths: Sequence[str]) -> List[Diagnostic]:
    """Lint every .py file under `paths` (files or directories)."""
    from .locks import iter_py_files

    diags: List[Diagnostic] = []
    for f in iter_py_files(paths):
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        lint = _Lint(os.path.relpath(f), src)
        lint.run()
        diags += lint.diags
    return diags


def default_lint_paths(repo_root: Optional[str] = None) -> List[str]:
    from .locks import default_lint_paths as _locks_paths

    return _locks_paths(repo_root)


def declared_guards(src: str) -> Dict[str, Dict[str, str]]:
    """class name -> {attr: lock attr} of the '# guarded-by:' comments
    in one source file — the shared parser the runtime sanitizer
    (analysis/sanitize.py) uses, so the static model and the dynamic
    assertions can never drift."""
    out: Dict[str, Dict[str, str]] = {}
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out
    directives = _Directives(src)
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        per: Dict[str, str] = {}
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name != "__init__":
                continue
            for st in ast.walk(fn):
                tgts = []
                if isinstance(st, ast.Assign):
                    tgts = st.targets
                elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
                    tgts = [st.target]
                decl = directives.decl_for(st) if tgts else None
                if decl is None:
                    continue
                for t in tgts:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        per[t.attr] = decl
        if per:
            out[cls.name] = per
    return out
