"""CLI driver: run every static-analysis pass over the repo and the
real book-example Programs.

    python -m paddle_tpu.analysis               # all passes, human output
    python -m paddle_tpu.analysis --json        # machine-readable
    python -m paddle_tpu.analysis --selftest    # every code fires on its
                                                # synthetic bad input
    python -m paddle_tpu.analysis --skip locks  # drop a pass
    python -m paddle_tpu.analysis --no-shapes   # skip V003/V004 re-eval

Exit status: nonzero iff any ERROR-level diagnostic (or a failing
selftest case). Warnings print but do not fail the run — the tier-1
gate is "no errors", matching the executor hook's refusal policy."""
from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu():
    """Static analysis must not require (or try to dial) a TPU: pin the
    jax platform before any backend initialization, the same way
    tests/conftest.py does."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.analysis")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as a JSON document")
    ap.add_argument("--selftest", action="store_true",
                    help="prove every diagnostic code fires on a "
                         "synthetic bad input")
    ap.add_argument("--skip", action="append", default=[],
                    choices=["verify", "locks", "guards", "invariants"],
                    help="skip a pass (repeatable)")
    ap.add_argument("--no-shapes", action="store_true",
                    help="skip the abstract-eval shape/dtype re-check "
                         "(V003/V004)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    args = ap.parse_args(argv)

    _force_cpu()

    from .diagnostics import ERROR
    from .selftest import run_selftest

    if args.selftest:
        results = run_selftest()
        ok = all(fired for _, fired, _ in results)
        if args.json:
            print(json.dumps({
                "selftest": [{"code": c, "fired": f} for c, f, _ in results],
                "ok": ok,
            }, indent=2))
        else:
            for code, fired, _diags in results:
                print(f"  {code}: {'fired' if fired else 'DID NOT FIRE'}")
            print(f"selftest: {len(results)} codes, "
                  f"{'all fired' if ok else 'SOME DID NOT FIRE'}")
        return 0 if ok else 1

    diags = []
    ran = []
    if "verify" not in args.skip:
        from .examples import build_all
        from .verify import verify_program

        ran.append("verify")
        for name, (main_prog, startup) in sorted(build_all().items()):
            for kind, prog in (("main", main_prog), ("startup", startup)):
                for d in verify_program(prog,
                                        check_shapes=not args.no_shapes):
                    d.where = f"{name}/{kind}: {d.where}"
                    diags.append(d)
    if "locks" not in args.skip:
        from .locks import default_lint_paths, lint_paths

        ran.append("locks")
        diags += lint_paths(default_lint_paths(args.root))
    if "guards" not in args.skip:
        from .guards import default_lint_paths as guard_paths
        from .guards import lint_paths as guard_lint

        ran.append("guards")
        diags += guard_lint(guard_paths(args.root))
    if "invariants" not in args.skip:
        from .invariants import check_repo

        ran.append("invariants")
        diags += check_repo(args.root)

    n_err = sum(1 for d in diags if d.severity == ERROR)
    n_warn = len(diags) - n_err
    if args.json:
        print(json.dumps({
            "passes": ran,
            "errors": n_err,
            "warnings": n_warn,
            "diagnostics": [d.to_dict() for d in diags],
        }, indent=2))
    else:
        for d in diags:
            print(d.format())
        print(f"[analysis] passes: {', '.join(ran)} — "
              f"{n_err} error(s), {n_warn} warning(s)")
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
