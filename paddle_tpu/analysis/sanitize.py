"""Runtime guard sanitizer — the dynamic half of the guards lint.

``PADDLE_TPU_SANITIZE=guards`` (read through ``FLAGS["sanitize"]``)
instruments the annotated runtime classes so every access to a
``# guarded-by:``-declared attribute asserts, at runtime, that the
declared lock is held. The declarations are parsed from SOURCE by the
same parser the static pass uses (``guards.declared_guards``), so the
static model and the dynamic assertions can never drift — the same
static-claim→runtime-check pairing ``verify_programs`` (executor gate)
and ``memory_optimize`` (liveness-proved rewrites) already use. With
the sanitizer on, every existing concurrency test (serving acceptance,
decode churn, chaos) doubles as a validator of the guard model.

Mechanics:

  - ``install()`` patches each registered class's ``__getattribute__``
    / ``__setattr__`` / ``__init__``; ``uninstall()`` restores the
    originals (tests toggle per-case).
  - Checks arm only AFTER ``__init__`` returns — construction is
    single-threaded, and declarations sit on ``__init__`` assignments
    whose locks may not exist yet.
  - "Held" is best-effort, matching ``threading.Condition._is_owned``:
    locks exposing ``_is_owned`` (RLock, Condition) answer exactly;
    a plain ``Lock`` is probed with a non-blocking acquire, which
    cannot distinguish *this* thread from another holder — the
    sanitizer therefore catches the common bug (access with the lock
    not held at all) and documents the residual blind spot rather than
    pretending to be a full happens-before TSan.
  - A violation raises ``GuardViolation`` (an AssertionError) AND is
    recorded in ``violations()`` — a scheduler thread that swallows
    the raise still leaves evidence a test can assert on.
  - Static ``# lint: allow-unguarded(attr)`` vets on the ACCESS line
    (or a comment block just above it) are honored at runtime too, so
    a deliberately lock-free access the guards lint accepts never
    trips the sanitizer (checked only on the violation path — clean
    accesses never read source).
"""
from __future__ import annotations

import inspect
import sys
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["GuardViolation", "install", "uninstall", "maybe_install",
           "enabled", "violations", "clear_violations", "install_class",
           "uninstall_class"]

# the annotated runtime surface: every class here carries # guarded-by
# declarations that the guards lint checks statically
_RUNTIME_CLASSES: Tuple[Tuple[str, str], ...] = (
    ("paddle_tpu.serving.decode", "DecodeEngine"),
    ("paddle_tpu.serving.engine", "InferenceEngine"),
    ("paddle_tpu.serving.registry", "ModelRegistry"),
    ("paddle_tpu.serving.kv_cache", "PageAllocator"),
    ("paddle_tpu.serving.kv_cache", "PrefixIndex"),
    ("paddle_tpu.serving.kv_cache", "HostSpillStore"),
    ("paddle_tpu.distributed.rpc", "_DedupCache"),
    ("paddle_tpu.distributed.rpc", "RpcClient"),
    ("paddle_tpu.distributed.param_server", "ParameterServer"),
    ("paddle_tpu.distributed.master", "MasterClient"),
    ("paddle_tpu.autotune.cache", "TuningCache"),
    ("paddle_tpu.autotune.ladder", "ShapeHistogram"),
    ("paddle_tpu.fleet.controller", "FleetController"),
    ("paddle_tpu.fleet.router", "FleetRouter"),
    ("paddle_tpu.fleet.member", "FleetMember"),
    ("paddle_tpu.fleet.policy", "FleetPolicy"),
    ("paddle_tpu.fleet.launcher", "ReplicaLauncher"),
    ("paddle_tpu.fleet.auth", "NonceWindow"),
    ("paddle_tpu.checkpoint.format", "CheckpointWriter"),
    ("paddle_tpu.mesh.observe", "_MeshStats"),
)

_ARMED_FLAG = "_guard_sanitizer_armed_"

_violations: List[str] = []
_violations_mu = threading.Lock()
_installed: Dict[type, Tuple] = {}


class GuardViolation(AssertionError):
    """A guarded attribute was accessed without its declared lock."""


def enabled() -> bool:
    from ..fluid.flags import FLAGS

    return FLAGS["sanitize"] == "guards"


def violations() -> List[str]:
    with _violations_mu:
        return list(_violations)


def clear_violations():
    with _violations_mu:
        _violations.clear()


def _lock_held(lock) -> bool:
    """Best-effort 'is this lock held' (see module docstring)."""
    is_owned = getattr(lock, "_is_owned", None)
    if callable(is_owned):
        try:
            return bool(is_owned())
        except Exception:  # pragma: no cover - exotic lock type
            pass
    acquire = getattr(lock, "acquire", None)
    if callable(acquire):
        if lock.acquire(False):
            lock.release()
            return False
        return True
    return True  # not a lock we can probe: never false-positive


# file -> guards._Directives, for honoring static allow-unguarded vets
# at runtime (only consulted on the violation path — zero cost clean)
_directive_cache: Dict[str, object] = {}


def _site_vetted(attr: str) -> bool:
    """Does the ACCESSING source line carry a
    '# lint: allow-unguarded(attr)' vet? Mirrors the static pass so a
    statically-vetted deliberate lock-free access never trips the
    runtime check. (Line-level only: a def-line vet must be repeated on
    the access line — or in a comment block just above it — to cover
    the runtime side.)"""
    from .guards import _Directives

    f = sys._getframe(1)
    here = __file__
    for _ in range(6):  # skip sanitize.py's own wrapper frames
        if f is None:
            return False
        if f.f_code.co_filename != here:
            break
        f = f.f_back
    if f is None:
        return False
    fname = f.f_code.co_filename
    d = _directive_cache.get(fname)
    if d is None:
        try:
            with open(fname, "r", encoding="utf-8") as fh:
                d = _Directives(fh.read())
        except OSError:
            d = _Directives("")
        _directive_cache[fname] = d
    return d.allows(attr, f.f_lineno)


def _note_violation(cls_name: str, attr: str, guard: str, kind: str):
    msg = (f"guard sanitizer: {cls_name}.{attr} {kind} without its "
           f"declared guard '{guard}' held "
           f"(thread {threading.current_thread().name})")
    with _violations_mu:
        _violations.append(msg)
    raise GuardViolation(msg)


def _declarations(cls) -> Dict[str, str]:
    """attr -> guard-lock attr name, parsed from the class's source by
    the static pass's parser."""
    from .guards import declared_guards

    try:
        src = inspect.getsource(inspect.getmodule(cls))
    except (OSError, TypeError):  # pragma: no cover - frozen/interactive
        return {}
    return declared_guards(src).get(cls.__name__, {})


def install_class(cls) -> bool:
    """Instrument one class in place. Returns True if it carried any
    declarations (and was patched)."""
    if cls in _installed:
        return True
    guarded = _declarations(cls)
    if not guarded:
        return False
    orig_init = cls.__init__
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__
    cls_name = cls.__name__

    def _check(self, name, kind):
        try:
            armed = _ARMED_FLAG in orig_get(self, "__dict__")
        except AttributeError:  # pragma: no cover - __slots__ classes
            armed = False
        if not armed:
            return
        guard_name = guarded[name]
        try:
            lock = orig_get(self, guard_name)
        except AttributeError:
            # guard not constructed (partial init), or a declaration
            # naming a module-level lock (unreachable through self —
            # the static pass still checks those): nothing to assert
            return
        if not _lock_held(lock) and not _site_vetted(name):
            _note_violation(cls_name, name, guard_name, kind)

    def __init__(self, *args, **kw):
        orig_init(self, *args, **kw)
        # arm via the original setattr: arming must not self-trip
        orig_set(self, _ARMED_FLAG, True)

    def __getattribute__(self, name):
        if name in guarded:
            _check(self, name, "read")
        return orig_get(self, name)

    def __setattr__(self, name, value):
        if name in guarded:
            _check(self, name, "written")
        orig_set(self, name, value)

    _installed[cls] = (orig_init, orig_get, orig_set)
    cls.__init__ = __init__
    cls.__getattribute__ = __getattribute__
    cls.__setattr__ = __setattr__
    return True


def uninstall_class(cls):
    orig = _installed.pop(cls, None)
    if orig is None:
        return
    cls.__init__, cls.__getattribute__, cls.__setattr__ = orig


def install() -> List[str]:
    """Instrument every registered runtime class; returns the list of
    instrumented 'module.Class' names."""
    import importlib

    done = []
    for mod_name, cls_name in _RUNTIME_CLASSES:
        mod = importlib.import_module(mod_name)
        cls = getattr(mod, cls_name)
        if install_class(cls):
            done.append(f"{mod_name}.{cls_name}")
    return done


def uninstall():
    for cls in list(_installed):
        uninstall_class(cls)


def maybe_install() -> bool:
    """The process-start hook (paddle_tpu/__init__): instrument iff
    FLAGS['sanitize'] (env PADDLE_TPU_SANITIZE) says 'guards'."""
    if not enabled():
        return False
    install()
    return True
