"""Invariant lint — cross-checks for stringly-typed registries that
drift silently.

Three registries in this repo are keyed by bare strings, with the
producers and consumers in different files (and different processes):

  - fault-injection sites: `faults.fire("<site>")` calls in the runtime
    vs. the `kind@site:sel` specs tests and tools install;
  - metric / gauge / histogram / span names: `metrics.counter("x.y")` /
    `tracing.span("x.y")` registrations in `paddle_tpu/` vs. the names
    tests assert on and docs document;
  - FLAGS keys: `FLAGS["k"]` reads vs. the keys defined in
    `fluid/flags.py`.

A renamed counter or a typo'd fault site today fails nothing — the test
silently asserts on a never-incremented metric. This pass makes the
drift a CI failure:

    N201 (error)   fault spec names a site no injection point declares
    N202 (error)   metric/span name asserted in tests or documented in
                   docs that no source registration declares
    N203 (error)   FLAGS key read/written that fluid/flags.py does not
                   define
    N204 (warning) FLAGS key defined but never read anywhere
    N205 (error)   an instance-keyed gauge registration (an f-string
                   gauge name — `<model>.v<version>` directly, or via a
                   label variable like the KV pool's `{sfx}`) with no
                   zero-at-retirement `.set(0)` site outside `__init__`
                   in the same class — the PR 5/6 gauge-clobber class:
                   during a hot-swap drain the old version's final
                   value would linger (or clobber the live engine's)
                   forever

Suppress a deliberate bad name (grammar tests, docs of removed names)
with `# lint: allow-site` / `# lint: allow-name` on the same line
(docs: `<!-- lint: allow-name -->` anywhere on the line); a versioned
gauge whose lifetime really is the process's with
`# lint: allow-unzeroed`.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .diagnostics import ERROR, WARNING, Diagnostic

PASS_NAME = "invariants"


def _d(code, sev, msg, where="", hint=""):
    return Diagnostic(code=code, severity=sev, message=msg, where=where,
                      hint=hint, pass_name=PASS_NAME)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _py_files(*dirs: str) -> List[str]:
    out: List[str] = []
    for d in dirs:
        if os.path.isfile(d) and d.endswith(".py"):
            out.append(d)
            continue
        for root, _subdirs, names in os.walk(d):
            if "__pycache__" in root:
                continue
            out += [os.path.join(root, n) for n in sorted(names)
                    if n.endswith(".py")]
    return sorted(set(out))


def _read(path: str) -> str:
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def _parse(path: str, src: Optional[str] = None):
    try:
        return ast.parse(src if src is not None else _read(path),
                         filename=path)
    except SyntaxError:
        return None


def _suppressed_lines(src: str, token: str) -> Set[int]:
    out = set()
    for i, line in enumerate(src.splitlines(), start=1):
        if f"lint: {token}" in line:
            out.add(i)
    return out


def _joinedstr_pattern(node: ast.JoinedStr) -> str:
    """f"handler.{method}" -> 'handler.*' (wildcard per placeholder)."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    return "".join(parts)


def _match(name: str, exact: Set[str], patterns: Set[str]) -> bool:
    if name in exact:
        return True
    for pat in patterns:
        if re.fullmatch(re.escape(pat).replace(r"\*", r"[^\s]+"), name):
            return True
    return False


# --- fault sites -------------------------------------------------------

_SPEC_RULE_RE = re.compile(
    r"(?:refuse|drop|delay|error|crash)@([\w.\-]+):")


def collect_declared_sites(pkg_dir: str) -> Tuple[Set[str], Set[str]]:
    """(exact sites, wildcard patterns) from `faults.fire(...)` /
    `_faults.fire(...)` call sites in the runtime package."""
    exact: Set[str] = set()
    patterns: Set[str] = set()
    for path in _py_files(pkg_dir):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "fire"):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                exact.add(arg.value)
            elif isinstance(arg, ast.JoinedStr):
                patterns.add(_joinedstr_pattern(arg))
    return exact, patterns


def collect_used_sites(paths: Iterable[str]
                       ) -> List[Tuple[str, str, int, bool]]:
    """(site, file, line, suppressed) for every `kind@site:` occurrence
    inside string constants of the given files/dirs."""
    out: List[Tuple[str, str, int, bool]] = []
    for path in _py_files(*paths):
        src = _read(path)
        suppressed = _suppressed_lines(src, "allow-site")
        tree = _parse(path, src)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for m in _SPEC_RULE_RE.finditer(node.value):
                    out.append((m.group(1), path, node.lineno,
                                node.lineno in suppressed))
            elif isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if isinstance(v, ast.Constant) and \
                            isinstance(v.value, str):
                        for m in _SPEC_RULE_RE.finditer(v.value):
                            out.append((m.group(1), path, node.lineno,
                                        node.lineno in suppressed))
    return out


def check_fault_sites(declared: Tuple[Set[str], Set[str]],
                      used: List[Tuple[str, str, int, bool]]
                      ) -> List[Diagnostic]:
    exact, patterns = declared
    diags: List[Diagnostic] = []
    for site, path, line, suppressed in used:
        if suppressed:
            continue
        if _match(site, exact, patterns):
            continue
        diags.append(_d(
            "N201", ERROR,
            f"fault spec targets site '{site}', but no "
            "faults.fire() call declares it",
            where=f"{os.path.relpath(path, _repo_root())}:{line}",
            hint="declared sites: " + ", ".join(
                sorted(exact | patterns)) +
            "; annotate '# lint: allow-site' for grammar-only specs"))
    return diags


# --- metric / span names ----------------------------------------------

_METRIC_FNS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_*]+)+$")


def collect_declared_names(pkg_dir: str) -> Tuple[Set[str], Set[str]]:
    """(exact, patterns) of metric AND span registrations in the
    package: literal or f-string first args of metrics.counter/gauge/
    histogram and tracing.span calls."""
    exact: Set[str] = set()
    patterns: Set[str] = set()
    for path in _py_files(pkg_dir):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name not in _METRIC_FNS and name != "span":
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                exact.add(arg.value)
            elif isinstance(arg, ast.JoinedStr):
                patterns.add(_joinedstr_pattern(arg))
    return exact, patterns


def _sanitize(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


class NameUniverse:
    """Everything a dotted name in a test or doc may legitimately refer
    to: declared metrics/spans (dotted or prometheus-sanitized), fault
    sites, or an actual attribute of a paddle_tpu module (docs name
    functions like `tracing.note_clock_offset` in the same backtick
    style)."""

    # first path segment -> module to getattr against
    _MODULES = {
        "tracing": "paddle_tpu.observability.tracing",
        "metrics": "paddle_tpu.observability.metrics",
        "timeline": "paddle_tpu.observability.timeline",
        "debug_server": "paddle_tpu.observability.debug_server",
        "faults": "paddle_tpu.distributed.faults",
        "elastic": "paddle_tpu.distributed.elastic",
        "master": "paddle_tpu.distributed.master",
        "fluid": "paddle_tpu.fluid",
        "executor": "paddle_tpu.fluid.executor",
        "io": "paddle_tpu.fluid.io",
        "serving": "paddle_tpu.serving",
        "autotune": "paddle_tpu.autotune",
        "fleet": "paddle_tpu.fleet",
        "checkpoint": "paddle_tpu.checkpoint",
        "mesh": "paddle_tpu.mesh",
    }

    def __init__(self, names: Tuple[Set[str], Set[str]],
                 sites: Tuple[Set[str], Set[str]]):
        self.exact, self.patterns = names
        self.site_exact, self.site_patterns = sites
        self.sanitized = {_sanitize(n) for n in self.exact}
        # sanitize but keep the wildcard character live
        self.sanitized_patterns = {
            "".join(c if (c.isalnum() or c in "_:*") else "_" for c in p)
            for p in self.patterns}
        # prefixes that make a dotted string "one of ours"
        self.prefixes = {n.split(".", 1)[0] for n in self.exact} | \
            {p.split(".", 1)[0] for p in self.patterns if "*" not in
             p.split(".", 1)[0]}

    def claims(self, name: str) -> bool:
        """Does this dotted name LOOK like one of our registry names
        (and therefore must resolve)?"""
        return _NAME_RE.match(name) is not None and \
            name.split(".", 1)[0] in self.prefixes

    def resolves(self, name: str) -> bool:
        if _match(name, self.exact, self.patterns):
            return True
        if _match(name, self.site_exact, self.site_patterns):
            return True
        if "_" in name and _match(name, self.sanitized,
                                  self.sanitized_patterns):
            return True
        # module attribute (docs reference code in the same style)
        head, _, rest = name.partition(".")
        mod_name = self._MODULES.get(head)
        if mod_name and rest:
            try:
                import importlib

                obj = importlib.import_module(mod_name)
                for part in rest.split("."):
                    obj = getattr(obj, part)
                return True
            except (ImportError, AttributeError):
                return False
        return False


def collect_test_name_refs(paths: Iterable[str], universe: NameUniverse
                           ) -> List[Tuple[str, str, int, bool]]:
    out: List[Tuple[str, str, int, bool]] = []
    for path in _py_files(*paths):
        src = _read(path)
        suppressed = _suppressed_lines(src, "allow-name")
        tree = _parse(path, src)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    universe.claims(node.value):
                out.append((node.value, path, node.lineno,
                            node.lineno in suppressed))
    return out


_BACKTICK_RE = re.compile(r"`([^`\s]+)`")


def collect_doc_name_refs(doc_paths: Iterable[str], universe: NameUniverse
                          ) -> List[Tuple[str, str, int, bool]]:
    out: List[Tuple[str, str, int, bool]] = []
    for path in doc_paths:
        if not os.path.exists(path):
            continue
        for lineno, line in enumerate(_read(path).splitlines(), start=1):
            suppressed = "lint: allow-name" in line
            for m in _BACKTICK_RE.finditer(line):
                token = m.group(1).strip("*`,.;:()")
                if universe.claims(token):
                    out.append((token, path, lineno, suppressed))
    return out


def check_names(universe: NameUniverse,
                refs: List[Tuple[str, str, int, bool]]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    seen: Set[Tuple[str, str, int]] = set()
    for name, path, line, suppressed in refs:
        if suppressed or universe.resolves(name):
            continue
        key = (name, path, line)
        if key in seen:
            continue
        seen.add(key)
        diags.append(_d(
            "N202", ERROR,
            f"name '{name}' is asserted/documented but no "
            "metrics.counter/gauge/histogram or tracing.span "
            "registration declares it",
            where=f"{os.path.relpath(path, _repo_root())}:{line}",
            hint="rename the reference to the registered name, or "
                 "annotate 'lint: allow-name' if deliberate"))
    return diags


# --- FLAGS keys --------------------------------------------------------

def _readthrough_keys(flags_path: str) -> Set[str]:
    """Keys _Flags.__getitem__ special-cases via `k == "..."` dispatch
    — defined (and consumed) without ever appearing in a subscript."""
    tree = _parse(flags_path)
    out: Set[str] = set()
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Name) and node.left.id == "k":
            for cmp in node.comparators:
                if isinstance(cmp, ast.Constant) and \
                        isinstance(cmp.value, str):
                    out.add(cmp.value)
    return out


def collect_defined_flags(flags_path: str) -> Set[str]:
    """Literal keys of the FLAGS dict in fluid/flags.py (including the
    read-through keys its _Flags.__getitem__ special-cases)."""
    tree = _parse(flags_path)
    defined: Set[str] = set(_readthrough_keys(flags_path))
    if tree is None:
        return defined
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if targets and any(isinstance(t, ast.Name) and t.id == "FLAGS"
                           for t in targets):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            defined.add(k.value)
    return defined


def collect_flag_refs(paths: Iterable[str], skip_files: Set[str] = frozenset()
                      ) -> List[Tuple[str, str, int, str]]:
    """(key, file, line, kind) of FLAGS["k"] subscripts, get_flag("k")
    / effective_flag("k") calls (the tuner read-through is still a
    FLAGS read — the entry is its cold-cache default), and
    set_flags({"k": ...}) literal keys."""
    out: List[Tuple[str, str, int, str]] = []
    for path in _py_files(*paths):
        if os.path.abspath(path) in skip_files:
            continue
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Subscript):
                base = node.value
                base_name = base.attr if isinstance(base, ast.Attribute) \
                    else (base.id if isinstance(base, ast.Name) else None)
                if base_name == "FLAGS" and \
                        isinstance(node.slice, ast.Constant) and \
                        isinstance(node.slice.value, str):
                    kind = "write" if isinstance(
                        getattr(node, "ctx", None), ast.Store) else "read"
                    out.append((node.slice.value, path, node.lineno, kind))
            elif isinstance(node, ast.Call):
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if name in ("get_flag", "effective_flag") and node.args \
                        and isinstance(node.args[0], ast.Constant):
                    out.append((node.args[0].value, path, node.lineno,
                                "read"))
                elif name == "set_flags" and node.args and \
                        isinstance(node.args[0], ast.Dict):
                    for k in node.args[0].keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            out.append((k.value, path, node.lineno, "write"))
    return out


def check_flags(defined: Set[str],
                refs: List[Tuple[str, str, int, str]],
                warn_unread: bool = True) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    read_keys = {k for k, _p, _l, kind in refs if kind == "read"}
    for key, path, line, _kind in refs:
        if key not in defined:
            diags.append(_d(
                "N203", ERROR,
                f"FLAGS key '{key}' is not defined in fluid/flags.py",
                where=f"{os.path.relpath(path, _repo_root())}:{line}",
                hint="defined keys: " + ", ".join(sorted(defined))))
    if warn_unread:
        for key in sorted(defined - read_keys):
            diags.append(_d(
                "N204", WARNING,
                f"FLAGS key '{key}' is defined but never read",
                where="paddle_tpu/fluid/flags.py",
                hint="dead configuration surface — wire it up or "
                     "remove it"))
    return diags


# --- instance-keyed gauges (N205) --------------------------------------

def check_versioned_gauge_source(src: str, path: str = "<src>"
                                 ) -> List[Diagnostic]:
    """N205 over one source file: every class attribute assigned a
    gauge with an INTERPOLATED (f-string) name — a per-instance series,
    whether the key is spelled `<model>.v<version>` directly or built
    through a label variable (`f"serving.kv.pages_used{sfx}"`) — must
    have a `self.<attr>.set(0)` zero-at-retirement site in the same
    class, OUTSIDE `__init__` (an init-time zero is initialization, not
    retirement, and would let the clobber class back in)."""
    diags: List[Diagnostic] = []
    tree = _parse(path, src)
    if tree is None:
        return diags
    suppressed = _suppressed_lines(src, "allow-unzeroed")
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        registered: List[Tuple[str, str, int]] = []  # attr, pattern, line
        zeroed: Set[str] = set()
        init_nodes: Set[int] = set()
        # registration and zero site must be in the SAME class: nodes of
        # nested ClassDefs are excluded (a nested class's same-named
        # `self._g.set(0)` must not satisfy the outer class's rule)
        nested: Set[int] = set()
        for sub in ast.walk(cls):
            if isinstance(sub, ast.ClassDef) and sub is not cls:
                nested |= {id(x) for x in ast.walk(sub)}
        for meth in cls.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and meth.name == "__init__":
                init_nodes = {id(sub) for sub in ast.walk(meth)}
        for node in ast.walk(cls):
            if id(node) in nested:
                continue
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and isinstance(node.targets[0].value, ast.Name) \
                    and node.targets[0].value.id == "self" \
                    and isinstance(node.value, ast.Call):
                fn = node.value.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if name == "gauge" and node.value.args and \
                        isinstance(node.value.args[0], ast.JoinedStr):
                    pat = _joinedstr_pattern(node.value.args[0])
                    if "*" in pat:  # >=1 interpolated segment
                        # a suppression anywhere on the (possibly
                        # multi-line) registration statement counts
                        span = range(node.lineno,
                                     (node.end_lineno or node.lineno) + 1)
                        if not any(ln in suppressed for ln in span):
                            registered.append(
                                (node.targets[0].attr, pat, node.lineno))
            if isinstance(node, ast.Call) and \
                    id(node) not in init_nodes and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "set" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value in (0, 0.0):
                base = node.func.value
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self":
                    zeroed.add(base.attr)
        for attr, pat, line in registered:
            if attr in zeroed:
                continue
            diags.append(_d(
                "N205", ERROR,
                f"instance-keyed gauge '{pat}' (self.{attr} in class "
                f"{cls.name}) has no zero-at-retirement site: no "
                f"'self.{attr}.set(0)' outside __init__ anywhere in "
                "the class",
                where=f"{path}:{line}",
                hint="zero the gauge when the owning engine/version "
                     "retires (the hot-swap drain otherwise leaves the "
                     "old version's last value lingering as live "
                     "occupancy — the PR 5/6 gauge-clobber bug class); "
                     "or annotate '# lint: allow-unzeroed' if the "
                     "series genuinely lives as long as the process"))
    return diags


def check_versioned_gauges(pkg_dir: str) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for path in _py_files(pkg_dir):
        rel = os.path.relpath(path, _repo_root())
        diags += check_versioned_gauge_source(_read(path), rel)
    return diags


# --- driver ------------------------------------------------------------

def check_repo(root: Optional[str] = None) -> List[Diagnostic]:
    root = root or _repo_root()
    pkg = os.path.join(root, "paddle_tpu")
    tests = os.path.join(root, "tests")
    tools = os.path.join(root, "tools")
    docs = [os.path.join(root, "docs", n)
            for n in ("OBSERVABILITY.md", "FAULT_TOLERANCE.md",
                      "STATIC_ANALYSIS.md", "SERVING.md", "AUTOTUNE.md",
                      "FLEET.md", "CHECKPOINT.md", "MESH.md")]
    diags: List[Diagnostic] = []

    sites = collect_declared_sites(pkg)
    diags += check_fault_sites(
        sites, collect_used_sites([tests, tools, os.path.join(pkg)]))

    universe = NameUniverse(collect_declared_names(pkg), sites)
    refs = collect_test_name_refs([tests], universe)
    refs += collect_doc_name_refs(docs, universe)
    diags += check_names(universe, refs)

    flags_path = os.path.join(pkg, "fluid", "flags.py")
    defined = collect_defined_flags(flags_path)
    refs2 = collect_flag_refs(
        [pkg, tests, tools, os.path.join(root, "benchmarks")])
    # read-through keys ('trace'/'trace_buffer'/'faults') are consumed
    # inside _Flags.__getitem__ via `k == "..."` dispatch, not a
    # subscript — count them as read so N204 doesn't cry wolf
    refs2 += [(k, flags_path, 0, "read")
              for k in _readthrough_keys(flags_path)]
    diags += check_flags(defined, refs2)

    diags += check_versioned_gauges(pkg)
    return diags
