"""Shared diagnostic model for the static-analysis passes.

Every pass (verify / locks / invariants) reports `Diagnostic` records —
a stable CODE, a severity, a human message, a location, and a fix hint —
so the CLI driver, the executor's pre-run hook, and tests all consume
one shape. Codes are namespaced by pass:

    Vxxx  program verifier        (analysis/verify.py)
    Lxxx  concurrency lint        (analysis/locks.py)
    Nxxx  invariant lint          (analysis/invariants.py)

The catalog (docs/STATIC_ANALYSIS.md) documents each code; the CLI's
``--selftest`` proves every code still fires on a synthetic bad input.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass
class Diagnostic:
    code: str           # e.g. "V001"
    severity: str       # ERROR | WARNING
    message: str
    where: str = ""     # "block 0 / op 3 (mul)" or "file.py:42"
    hint: str = ""
    pass_name: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def key(self):
        """Identity for before/after comparisons (the transpiler gate)."""
        return (self.code, self.where, self.message)

    def format(self) -> str:
        sev = self.severity.upper()
        loc = f" [{self.where}]" if self.where else ""
        hint = f" (hint: {self.hint})" if self.hint else ""
        return f"{sev} {self.code}{loc}: {self.message}{hint}"


def errors(diags: Iterable[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == ERROR]


def warnings(diags: Iterable[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == WARNING]


class AnalysisError(RuntimeError):
    """Raised when a gate (executor pre-run hook, transpiler rewrite
    check) refuses to proceed over error-level diagnostics. Carries the
    diagnostics so callers/tests can assert on codes."""

    def __init__(self, header: str, diags: List[Diagnostic]):
        self.diagnostics = list(diags)
        lines = [header] + ["  " + d.format() for d in self.diagnostics]
        super().__init__("\n".join(lines))


# Backwards-friendly alias: the verifier's gate raises this name.
ProgramVerifyError = AnalysisError
