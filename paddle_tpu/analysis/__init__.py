"""paddle_tpu.analysis — static analysis for the define-then-run stack.

Three passes and one driver (see docs/STATIC_ANALYSIS.md for the full
catalog and CLI usage):

  - `verify` — Program/IR verifier (V0xx): runs between graph
    construction and lowering; `FLAGS["verify_programs"]` gates the
    executor on it, and the memory-optimization transpiler proves its
    rewrites against it.
  - `locks` — concurrency lint (L101–L103): lock-order graph +
    blocking-call-under-lock over the distributed/serving/observability
    runtime.
  - `guards` — shared-state race lint (L104–L106, "TSan-lite"):
    guarded-by inference + declarations over the same modules, with a
    runtime sanitizer twin (PADDLE_TPU_SANITIZE=guards,
    analysis/sanitize.py) asserting the declared guards at attribute
    access.
  - `invariants` — registry drift lint (N2xx): fault sites, metric/span
    names, FLAGS keys, per-version gauge retirement.

CLI: ``python -m paddle_tpu.analysis [--json] [--selftest]``.
"""
from .diagnostics import (  # noqa: F401
    ERROR, WARNING, AnalysisError, Diagnostic, ProgramVerifyError,
    errors, warnings,
)
from .verify import assert_valid, verify_program  # noqa: F401

__all__ = [
    "ERROR", "WARNING", "AnalysisError", "Diagnostic",
    "ProgramVerifyError", "errors", "warnings",
    "assert_valid", "verify_program",
]
