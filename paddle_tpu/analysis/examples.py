"""Real book-example Program builders for the analysis passes.

These are the same model graphs the book tests train (fit_a_line,
recognize_digits LeNet, word2vec, understand_sentiment), built WITHOUT
datasets or training — the verifier needs the IR, not the data. The CLI
driver and tier-1 tests run `verify_program` over every one of them, so
a verifier regression (or a layer/backward change that emits a
malformed graph) fails the moment it lands.

Each builder returns (main, startup); `build_all()` returns a dict of
name -> (main, startup)."""
from __future__ import annotations

from typing import Callable, Dict, Tuple


def _programs():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import unique_name
    from paddle_tpu.fluid.framework import Program, program_guard

    main, startup = Program(), Program()
    main.random_seed = startup.random_seed = 7
    return fluid, unique_name, main, startup, program_guard


def build_fit_a_line():
    """reference tests/book/test_fit_a_line.py — linear regression."""
    fluid, unique_name, main, startup, program_guard = _programs()
    from paddle_tpu.fluid import layers

    with unique_name.guard(), program_guard(main, startup):
        x = layers.data(name="x", shape=[13], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        y_predict = layers.fc(input=x, size=1)
        cost = layers.square_error_cost(input=y_predict, label=y)
        avg_cost = layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
    return main, startup


def build_recognize_digits_conv():
    """reference tests/book/test_recognize_digits.py (conv variant)."""
    fluid, unique_name, main, startup, program_guard = _programs()
    from paddle_tpu.fluid import layers
    from paddle_tpu.models import lenet

    with unique_name.guard(), program_guard(main, startup):
        img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        avg_cost, acc, prediction = lenet.build(img, label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    return main, startup


def build_word2vec(dict_size: int = 200, embed_size: int = 16,
                   hidden_size: int = 32, n: int = 5):
    """reference tests/book/test_word2vec.py — n-gram next-word model
    with a shared embedding table."""
    fluid, unique_name, main, startup, program_guard = _programs()
    from paddle_tpu.fluid import layers

    with unique_name.guard(), program_guard(main, startup):
        words = [layers.data(name=f"word_{i}", shape=[1], dtype="int64")
                 for i in range(n - 1)]
        next_word = layers.data(name="next_word", shape=[1], dtype="int64")
        embeds = [
            layers.embedding(input=w, size=[dict_size, embed_size],
                             param_attr=fluid.ParamAttr(name="shared_w"))
            for w in words
        ]
        concat = layers.concat(input=embeds, axis=1)
        hidden = layers.fc(input=concat, size=hidden_size, act="sigmoid")
        logits = layers.fc(input=hidden, size=dict_size)
        cost = layers.softmax_with_cross_entropy(logits=logits,
                                                 label=next_word)
        avg_cost = layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)
    return main, startup


def build_understand_sentiment_conv(dict_dim: int = 100, emb_dim: int = 16,
                                    hid_dim: int = 16, class_dim: int = 2):
    """reference tests/book/test_understand_sentiment.py
    (convolution_net)."""
    fluid, unique_name, main, startup, program_guard = _programs()
    from paddle_tpu.fluid import layers, nets

    with unique_name.guard(), program_guard(main, startup):
        data = layers.data(name="words", shape=[1], dtype="int64",
                           lod_level=1)
        label = layers.data(name="label", shape=[1], dtype="int64")
        emb = layers.embedding(input=data, size=[dict_dim, emb_dim])
        conv_3 = nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                         filter_size=3, act="tanh",
                                         pool_type="sqrt")
        conv_4 = nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                         filter_size=4, act="tanh",
                                         pool_type="sqrt")
        merged = layers.concat(input=[conv_3, conv_4], axis=1)
        logits = layers.fc(input=merged, size=class_dim)
        cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
        avg_cost = layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)
    return main, startup


BOOK_EXAMPLES: Dict[str, Callable[[], Tuple[object, object]]] = {
    "fit_a_line": build_fit_a_line,
    "recognize_digits_conv": build_recognize_digits_conv,
    "word2vec": build_word2vec,
    "understand_sentiment_conv": build_understand_sentiment_conv,
}


def build_all() -> Dict[str, Tuple[object, object]]:
    return {name: fn() for name, fn in BOOK_EXAMPLES.items()}
