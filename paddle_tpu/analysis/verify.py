"""Program verifier — IR well-formedness checks between graph
construction and lowering (the role TVM-style compiler stacks give a
first-class IR verification pass).

paddle_tpu's define-then-run design means a malformed Program (a
dangling input, a transpiler rewrite that aliases a live buffer, a desc
whose declared shape drifted from what the emitter computes) surfaces
only as a cryptic JAX trace error — or a silently wrong result — at
step time. `verify_program` walks a `fluid.Program` block-by-block and
reports structured diagnostics instead:

    V001 (error)   use-before-def: a non-persistable var is read before
                   the op that first produces it
    V002 (error)   unknown var: an op names a variable that exists in no
                   reachable block scope
    V003 (error)   shape mismatch: declared output shape contradicts the
                   op emitter's abstract evaluation (or the emitter
                   rejects fully-known input shapes outright)
    V004 (error)   dtype mismatch: declared output dtype contradicts the
                   emitter's abstract evaluation
    V005 (warning) grad pairing: an `x@GRAD` var with no forward `x`
    V006 (warning) dead var/op: computed but never consumed (fetch
                   targets are runtime-injected, so this stays a warning)
    V007 (warning) write-after-write: a var is overwritten with no
                   intervening read (the first write is dead)
    V008 (error)   control-flow nesting: bad parent chain or a sub-block
                   attr referencing a nonexistent/ill-parented block
    V009 (error)   unknown op type: no emitter registered and not a host
                   op the executor handles outside the device program
    V010 (error)   unsafe buffer reuse: a memory-optimization merge
                   aliases a variable whose live range has not ended
                   (reported by `check_reuse_events`, the transpiler gate)

Severities are chosen so the always-on executor hook
(`FLAGS["verify_programs"]`) only refuses programs that cannot run
correctly; style/deadness findings stay warnings for the CLI.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import (
    ERROR, WARNING, AnalysisError, Diagnostic, errors as _errors,
)

PASS_NAME = "verify"

GRAD_SUFFIX = "@GRAD"


def _d(code, sev, msg, where="", hint=""):
    return Diagnostic(code=code, severity=sev, message=msg, where=where,
                      hint=hint, pass_name=PASS_NAME)


def _host_op_types() -> Set[str]:
    """Ops the executor runs outside the device program (feed/fetch
    plumbing, readers, pserver transport, save/load) plus the
    delete_var liveness marker exec_op_descs interprets directly."""
    from ..fluid.executor import _SKIP_OP_TYPES

    return set(_SKIP_OP_TYPES) | {"delete_var"}


def _op_where(block, i, od) -> str:
    return f"block {block.idx} / op {i} ({od.type})"


def _is_known_type(od, ops_registry, host_ops) -> bool:
    if od.type in ops_registry or od.type in host_ops:
        return True
    if od.type.endswith("_grad"):
        from ..fluid.registry import FWD_META_ATTR

        meta = od.attrs.get(FWD_META_ATTR)
        base = meta.get("type") if isinstance(meta, dict) else od.type[:-5]
        return base in ops_registry
    return False


def _iter_names(io: Dict[str, List[str]]):
    for slot, names in io.items():
        for n in names:
            if n:
                yield slot, n


def _check_block_structure(program) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    n = len(program.blocks)
    for b in program.blocks:
        if b.idx == 0:
            if b.parent_idx >= 0:
                diags.append(_d("V008", ERROR,
                                f"global block has parent {b.parent_idx}",
                                where="block 0"))
            continue
        if not (0 <= b.parent_idx < b.idx):
            diags.append(_d(
                "V008", ERROR,
                f"block {b.idx} has parent {b.parent_idx} (must be a "
                "lower-numbered block: the parent chain may not cycle)",
                where=f"block {b.idx}"))
    for b in program.blocks:
        for i, op in enumerate(b.ops):
            od = op.desc
            for k, v in od.attrs.items():
                if not k.endswith("_block"):
                    continue
                idx = v.idx if hasattr(v, "idx") else v
                if not isinstance(idx, int) or not (0 <= idx < n):
                    diags.append(_d(
                        "V008", ERROR,
                        f"attr '{k}'={idx!r} names no block of this "
                        f"program ({n} blocks)",
                        where=_op_where(b, i, od),
                        hint="sub-block attrs must hold a valid block "
                             "index"))
                elif idx != 0 and program.blocks[idx].parent_idx != b.idx:
                    diags.append(_d(
                        "V008", WARNING,
                        f"attr '{k}' names block {idx}, whose parent is "
                        f"block {program.blocks[idx].parent_idx}, not the "
                        f"op's block {b.idx}",
                        where=_op_where(b, i, od)))
    return diags


def _shape_check_op(block, i, od, info) -> List[Diagnostic]:
    """Re-run the emitter's abstract evaluation against fully-known
    input shapes and compare with the declared output descs (the
    independent re-check of what Operator._infer_shapes wrote at build
    time — a transpiler or manual desc edit can have drifted since)."""
    import jax

    from ..fluid import core
    from ..fluid.registry import EmitCtx, normalize_outs

    diags: List[Diagnostic] = []
    structs = {}
    for slot, names in od.inputs.items():
        lst = []
        for n in names:
            if not n:
                lst.append(None)
                continue
            var = block._var_recursive(n)
            if var is None or var.shape is None:
                return []  # cannot infer
            if any(d is None or d < 0 for d in var.shape):
                return []  # unknown batch dims: trace time decides
            try:
                lst.append(jax.ShapeDtypeStruct(
                    tuple(var.shape), core.as_jnp_dtype(var.dtype)))
            except Exception:
                return []
        structs[slot] = lst
    attrs = od.attrs

    def absfn(ins):
        ctx = EmitCtx(root_key=jax.random.key(0), is_test=False)
        return normalize_outs(info.forward(ctx, ins, attrs))

    try:
        outs = jax.eval_shape(absfn, structs)
    except (TypeError, ValueError) as e:
        return [_d("V003", ERROR,
                   f"emitter rejects fully-known input shapes: {e}",
                   where=_op_where(block, i, od),
                   hint="the op's inputs were edited after build-time "
                        "inference ran")]
    except Exception:
        return []  # benign abstract-eval limits (collectives, concretization)
    for slot, names in od.outputs.items():
        shapes = outs.get(slot, [])
        for j, n in enumerate(names):
            if not n or j >= len(shapes) or shapes[j] is None:
                continue
            var = block._var_recursive(n)
            if var is None or var.shape is None:
                continue
            declared = tuple(var.shape)
            inferred = tuple(shapes[j].shape)
            if -1 not in declared and declared != inferred:
                diags.append(_d(
                    "V003", ERROR,
                    f"output '{n}' declares shape {declared} but the "
                    f"emitter computes {inferred}",
                    where=_op_where(block, i, od),
                    hint="re-run shape inference or fix the rewrite "
                         "that edited this desc"))
            want = core.convert_dtype(shapes[j].dtype)
            if var.dtype != want:
                diags.append(_d(
                    "V004", ERROR,
                    f"output '{n}' declares dtype {var.dtype} but the "
                    f"emitter computes {want}",
                    where=_op_where(block, i, od)))
    return diags


def verify_program(program, check_shapes: bool = True,
                   fetch_targets: Sequence[str] = ()) -> List[Diagnostic]:
    """Run every verifier check over `program`; returns diagnostics
    (possibly empty). `check_shapes=False` skips the (abstract-eval
    priced) V003/V004 re-inference — the mode the executor's per-compile
    hook uses. `fetch_targets` suppresses V006 for names the caller
    knows are fetched at runtime."""
    from ..fluid.framework import Parameter
    from ..fluid.registry import OPS

    host_ops = _host_op_types()
    diags: List[Diagnostic] = list(_check_block_structure(program))
    fetch_targets = set(fetch_targets)

    for b in program.blocks:
        # --- per-op existence / type / shape checks ---------------------
        for i, op in enumerate(b.ops):
            od = op.desc
            if not _is_known_type(od, OPS, host_ops):
                diags.append(_d(
                    "V009", ERROR,
                    f"no emitter registered for op type '{od.type}'",
                    where=_op_where(b, i, od),
                    hint="register_op() it, or add it to the executor's "
                         "host-op set if it must run outside the device "
                         "program"))
                continue
            for slot, n in _iter_names(od.inputs):
                if b._var_recursive(n) is None:
                    diags.append(_d(
                        "V002", ERROR,
                        f"input {slot}={n!r} exists in no reachable "
                        "block scope",
                        where=_op_where(b, i, od),
                        hint="create the var in this block (or an "
                             "ancestor) before referencing it"))
            for slot, n in _iter_names(od.outputs):
                if b._var_recursive(n) is None:
                    diags.append(_d(
                        "V002", ERROR,
                        f"output {slot}={n!r} exists in no reachable "
                        "block scope",
                        where=_op_where(b, i, od)))
            if check_shapes and od.type in OPS:
                info = OPS[od.type]
                if info.infer_shape is None and od.type not in host_ops:
                    diags.extend(_shape_check_op(b, i, od, info))

        # --- def/use ordering (global block only: sub-blocks re-execute,
        # so read-before-write there is a legitimate loop carry) --------
        first_def: Dict[str, int] = {}
        last_def: Dict[str, int] = {}
        for i, op in enumerate(b.ops):
            for _, n in _iter_names(op.desc.outputs):
                first_def.setdefault(n, i)
                last_def[n] = i
        if b.parent_idx < 0:
            for i, op in enumerate(b.ops):
                od = op.desc
                for slot, n in _iter_names(od.inputs):
                    var = b._var_recursive(n)
                    if var is None or var.persistable or \
                            isinstance(var, Parameter):
                        continue
                    fd = first_def.get(n)
                    if fd is not None and fd > i:
                        diags.append(_d(
                            "V001", ERROR,
                            f"input {slot}={n!r} is read at op {i} but "
                            f"first produced at op {fd}",
                            where=_op_where(b, i, od),
                            hint="reorder the ops, or feed/persist the "
                                 "var if the read is meant to see state"))

        # --- grad pairing ----------------------------------------------
        for name, var in b.vars.items():
            if not name.endswith(GRAD_SUFFIX):
                continue
            base = name[: -len(GRAD_SUFFIX)]
            if base and b._var_recursive(base) is None:
                diags.append(_d(
                    "V005", WARNING,
                    f"grad var '{name}' has no forward var '{base}' in "
                    "any reachable scope",
                    where=f"block {b.idx}",
                    hint="dangling grad slot — was the forward var "
                         "renamed or pruned without its gradient?"))

        # --- liveness: dead vars/ops and write-after-write --------------
        last_read: Dict[str, int] = {}  # name -> last read index in b
        for i, op in enumerate(b.ops):
            for _, n in _iter_names(op.desc.inputs):
                last_read[n] = i
        other_block_reads = _sub_block_reads(program, b)
        for i, op in enumerate(b.ops):
            od = op.desc
            if od.type in host_ops:
                continue
            out_names = [n for _, n in _iter_names(od.outputs)]
            in_names = {n for _, n in _iter_names(od.inputs)}
            dead_outs = []
            for n in out_names:
                var = b._var_recursive(n)
                if var is None or var.persistable or \
                        isinstance(var, Parameter):
                    continue
                if n in fetch_targets or n in in_names:
                    continue
                # dead: this is the final def and nothing — in this block
                # or any other — reads it afterwards
                if (last_def.get(n) == i and last_read.get(n, -1) <= i
                        and n not in other_block_reads):
                    dead_outs.append(n)
            if dead_outs and len(dead_outs) == len(out_names):
                diags.append(_d(
                    "V006", WARNING,
                    f"op computes only dead outputs {dead_outs} (never "
                    "read, not persistable)",
                    where=_op_where(b, i, od),
                    hint="dead code — or a fetch-only value; fetch "
                         "targets are runtime-injected so this is "
                         "advisory"))
            elif dead_outs:
                diags.append(_d(
                    "V006", WARNING,
                    f"outputs {dead_outs} are never read",
                    where=_op_where(b, i, od)))
        # WAW hazards
        writes: Dict[str, int] = {}
        for i, op in enumerate(b.ops):
            od = op.desc
            in_names = {n for _, n in _iter_names(od.inputs)}
            for n in in_names:
                writes.pop(n, None)  # read intervenes
            for _, n in _iter_names(od.outputs):
                var = b._var_recursive(n)
                if var is None:
                    continue
                prev = writes.get(n)
                if prev is not None and n not in in_names:
                    diags.append(_d(
                        "V007", WARNING,
                        f"'{n}' written at op {prev} is overwritten at "
                        f"op {i} with no intervening read",
                        where=_op_where(b, i, od),
                        hint="the first write is dead — drop it, or a "
                             "reader was pruned by mistake"))
                writes[n] = i
    return diags


def _sub_block_reads(program, block) -> Set[str]:
    out: Set[str] = set()
    for b in program.blocks:
        if b.idx == block.idx:
            continue
        for op in b.ops:
            out.update(n for _, n in _iter_names(op.desc.inputs))
    return out


def assert_valid(program, check_shapes: bool = False,
                 fetch_targets: Sequence[str] = (),
                 header: str = "program failed verification"):
    """Raise AnalysisError if `program` has error-level diagnostics —
    the executor's FLAGS["verify_programs"] pre-run hook."""
    diags = verify_program(program, check_shapes=check_shapes,
                           fetch_targets=fetch_targets)
    errs = _errors(diags)
    if errs:
        raise AnalysisError(header, errs)
    return diags


# --- memory-optimization reuse proof -----------------------------------

def check_reuse_events(cfg, events) -> List[Diagnostic]:
    """Prove a memory_optimize rewrite never aliases a still-live
    variable. `cfg` is the ControlFlowGraph built on the PRE-rewrite
    block; `events` is the transpiler's merge log: (op_index, out,
    cand) meaning "at op_index, var `out` was merged into (storage of)
    `cand`". Safe iff the storage's live range ended strictly before
    op_index; merges extend the storage's range by the merged var's
    original range."""
    last_use = dict(cfg.last_use_index())
    storage_last: Dict[str, int] = {}
    diags: List[Diagnostic] = []
    for (i, out, cand) in events:
        end = storage_last.get(cand, last_use.get(cand, -1))
        if end >= i:
            diags.append(_d(
                "V010", ERROR,
                f"reuse of '{cand}' for '{out}' at op {i} aliases a "
                f"live variable (storage still used at op {end})",
                where=f"op {i}",
                hint="the liveness analysis and the reuse pool "
                     "disagree — this rewrite would corrupt values"))
        storage_last[cand] = max(end, storage_last.get(out,
                                                       last_use.get(out, -1)))
    return diags


def verify_rewrite(program, before_diags, cfg, events,
                   what: str = "memory_optimize"):
    """Transpiler gate: fail if the rewrite introduced NEW error-level
    structural diagnostics, or if the reuse log fails the aliasing
    proof. `before_diags` is verify_program() output from before the
    rewrite (pre-existing issues are not the rewrite's fault)."""
    reuse = _errors(check_reuse_events(cfg, events))
    before = {d.key() for d in _errors(before_diags)}
    after = [d for d in _errors(verify_program(program, check_shapes=False))
             if d.key() not in before]
    bad = reuse + after
    if bad:
        raise AnalysisError(
            f"{what} produced an invalid rewrite (program left "
            "unusable — rebuild it)", bad)
