"""Concurrency lint — lock-acquisition graph + blocking-call-under-lock.

An AST pass over the lock-heavy runtime modules (`distributed/`,
`observability/` by default) that proves lock discipline statically
instead of waiting for the deadlock:

  - collects every `threading.Lock`/`RLock`/`Condition` attribute
    (`self._mu = threading.Lock()`, module-level `_clients_mu = ...`,
    dict-of-locks families like `self._param_locks[...]`), aliasing a
    `Condition(self._mu)` to the lock it wraps;
  - symbolically walks each function tracking the held-lock stack
    through `with` statements, recording which locks are acquired (and
    which blocking calls are reached) while other locks are held —
    including one level of same-class `self.method()` calls, closed
    transitively over the class's call graph;
  - reports:

    L101 (error)   lock-order cycle (or a declared-order violation): two
                   code paths acquire the same locks in opposite order
    L102 (error)   blocking call under a lock: `socket.recv`,
                   `RpcClient.call`, `time.sleep`, `Event.wait`,
                   `Thread.join`, frame IO ... reached while a lock is
                   held (a `Condition.wait` on the held condition itself
                   is exempt — it releases the lock while parked)
    L103 (error)   self-deadlock: a non-reentrant lock acquired while
                   already held on the same path (directly or through a
                   same-class call)

Vetted sites are annotated in source:

    # lint: allow-blocking        on the blocking call, its `with` line,
                                  or the function's `def` line
    # lint: allow-lock-order      excludes an acquisition edge from the
                                  order graph
    # lint: lock-order(a<b)       declares the intended order of two
                                  locks (short attr names); an observed
                                  b-then-a path becomes an L101 violation
    # lint: lock-alias            on a `self._mu = mu` assignment:
                                  the attribute IS a lock, injected by
                                  the owner (shared-lock composition —
                                  PrefixIndex runs under its
                                  allocator's mutex); registered as a
                                  lock attribute of the scope so
                                  guarded-by declarations may name it
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import ERROR, Diagnostic

PASS_NAME = "locks"

# attribute method names that block the calling thread
_BLOCKING_ATTRS = {
    "recv", "recv_into", "recvfrom", "accept", "connect",
    "create_connection", "sleep", "wait", "call", "serve_forever",
    "getaddrinfo", "select",
}
# bare-name calls that block (module-level helpers of the RPC framing)
_BLOCKING_NAMES = {
    "read_frame", "read_msg", "write_msg", "write_frame",
    "create_connection", "sleep",
}
_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore"}

_DIRECTIVE_RE = re.compile(r"#\s*lint:\s*([a-z\-]+)(?:\(([^)]*)\))?")


def _d(code, msg, where, hint=""):
    return Diagnostic(code=code, severity=ERROR, message=msg, where=where,
                      hint=hint, pass_name=PASS_NAME)


def _walk_own(fn_node):
    """Yield nodes of a function body WITHOUT descending into nested
    function/class definitions (they run later, under their own locks)."""
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _expr_text(node) -> Optional[str]:
    """Dotted/indexed text of a lock expression, or None if unresolvable.
    `self._param_locks[name]` -> 'self._param_locks[]' (a lock family)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_text(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Subscript):
        base = _expr_text(node.value)
        return f"{base}[]" if base else None
    return None


def _contains_lock_ctor(node) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name in _LOCK_CTORS or name == "Condition":
                return True
    return False


class _Directives:
    """Per-line `# lint:` comments of one source file."""

    def __init__(self, src: str):
        self.by_line: Dict[int, Set[str]] = {}
        self.order_decls: List[Tuple[str, str]] = []
        lines = src.splitlines()
        try:
            toks = tokenize.generate_tokens(io.StringIO(src).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                for m in _DIRECTIVE_RE.finditer(tok.string):
                    kind, arg = m.group(1), m.group(2)
                    if kind == "lock-order" and arg and "<" in arg:
                        a, b = (s.strip() for s in arg.split("<", 1))
                        self.order_decls.append((a, b))
                        continue
                    ln = tok.start[0]
                    self.by_line.setdefault(ln, set()).add(kind)
                    # a directive in a standalone comment (or a comment
                    # block) also covers the next code line below it
                    if lines[ln - 1].lstrip().startswith("#"):
                        j = ln
                        while j < len(lines) and (
                                not lines[j].strip()
                                or lines[j].lstrip().startswith("#")):
                            j += 1
                        if j < len(lines):
                            self.by_line.setdefault(j + 1, set()).add(kind)
        except tokenize.TokenError:
            pass

    def allows(self, kind: str, *lines: int) -> bool:
        return any(kind in self.by_line.get(ln, ()) for ln in lines if ln)


class _FnSummary:
    __slots__ = ("name", "node", "acquires", "blocking", "calls")

    def __init__(self, name, node):
        self.name = name
        self.node = node
        self.acquires: Set[str] = set()   # lock ids acquired anywhere inside
        self.blocking: bool = False       # reaches a blocking call
        self.calls: Set[str] = set()      # same-scope callee names


class _Scope:
    """One lint scope: a module's top level, or one class."""

    def __init__(self, qual: str):
        self.qual = qual                      # "rpc.RpcClient" / "rpc"
        self.locks: Dict[str, str] = {}       # expr text -> canonical id
        self.rlocks: Set[str] = set()         # canonical ids that reenter
        self.conditions: Set[str] = set()     # canonical ids that are Conditions
        self.fns: Dict[str, _FnSummary] = {}


class _Lint:
    def __init__(self, filename: str, src: str):
        self.filename = filename
        self.short = os.path.splitext(os.path.basename(filename))[0]
        self.src = src
        self.directives = _Directives(src)
        self.diags: List[Diagnostic] = []
        # global (per-run) lock-order edges: (a, b) -> (where, lines)
        self.edges: Dict[Tuple[str, str], Tuple[str, Tuple[int, ...]]] = {}

    def where(self, line: int) -> str:
        return f"{self.filename}:{line}"

    # --- lock discovery --------------------------------------------------
    def _scan_locks(self, scope: _Scope, body, self_name: str):
        """Find lock-attribute assignments anywhere in `body` (methods
        included: locks are usually created in __init__)."""
        for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = _expr_text(node.targets[0])
            if tgt is None:
                continue
            val = node.value
            # `self._mu = mu  # lint: lock-alias` — an injected shared
            # lock (the owner passes its own mutex in); same identity
            # rules as a constructed lock
            if self.directives.allows("lock-alias", node.lineno) and \
                    (tgt.startswith(self_name + ".") or "." not in tgt):
                cid = f"{scope.qual}.{tgt.split('.')[-1]}"
                scope.locks[tgt] = cid
                continue
            if isinstance(val, ast.Call):
                fn = val.func
                ctor = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if ctor in _LOCK_CTORS:
                    cid = f"{scope.qual}.{tgt.split('.')[-1]}" \
                        if tgt.startswith(self_name + ".") else \
                        f"{scope.qual}.{tgt}" if "." not in tgt else None
                    if cid:
                        scope.locks[tgt] = cid
                        if ctor == "RLock":
                            scope.rlocks.add(cid)
                    continue
                if ctor == "Condition":
                    # Condition(self._mu) shares _mu's identity; a bare
                    # Condition() owns a private lock
                    alias = None
                    if val.args:
                        alias = scope.locks.get(_expr_text(val.args[0]) or "")
                    cid = alias or (f"{scope.qual}.{tgt.split('.')[-1]}"
                                    if tgt.startswith(self_name + ".")
                                    or "." not in tgt else None)
                    if cid:
                        scope.locks[tgt] = cid
                        scope.conditions.add(cid)
                    continue
            # dict/comprehension of locks -> a family id
            if _contains_lock_ctor(val) and not isinstance(val, ast.Call):
                if tgt.startswith(self_name + ".") or "." not in tgt:
                    scope.locks[tgt + "[]"] = \
                        f"{scope.qual}.{tgt.split('.')[-1]}[]"

    # --- per-function symbolic walk --------------------------------------
    def _resolve_lock(self, scope: _Scope, node) -> Optional[str]:
        txt = _expr_text(node)
        if txt is None:
            return None
        return scope.locks.get(txt)

    def _summarize(self, scope: _Scope, fn: _FnSummary):
        for node in _walk_own(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    cid = self._resolve_lock(scope, item.context_expr)
                    if cid:
                        fn.acquires.add(cid)
            elif isinstance(node, ast.Call):
                if self._is_blocking_call(scope, node, held=None):
                    fn.blocking = True
                callee = self._self_callee(node)
                if callee:
                    fn.calls.add(callee)

    @staticmethod
    def _self_callee(node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self":
            return fn.attr
        return None

    def _is_blocking_call(self, scope: _Scope, node: ast.Call,
                          held: Optional[List[str]]) -> bool:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr not in _BLOCKING_ATTRS:
                return False
            if fn.attr == "wait":
                # Condition.wait on the held condition releases it — it
                # only parks other holders when MORE locks are held.
                # During summary (held=None) any wait counts as blocking;
                # the symbolic walk refines it.
                cid = self._resolve_lock(scope, fn.value)
                if held is not None and cid is not None and \
                        cid in held and len(held) == 1:
                    return False
            if fn.attr == "join":
                # keep str.join / os.path.join out: only thread-ish
                # receivers count
                txt = _expr_text(fn.value) or ""
                if "thread" not in txt.lower() and not any(
                        kw.arg == "timeout" for kw in node.keywords):
                    return False
            return True
        if isinstance(fn, ast.Name):
            return fn.id in _BLOCKING_NAMES
        return False

    def _walk_fn(self, scope: _Scope, fn: _FnSummary):
        def_line = fn.node.lineno

        def scan_exprs(node, held):
            """Check calls in an expression subtree (no statements inside
            except lambdas/comprehensions, which share the held set)."""
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and held:
                    self._check_call(scope, fn, sub, held, def_line)

        def visit(stmts, held: List[Tuple[str, int]]):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue  # runs later, with no inherited locks
                if isinstance(st, ast.With):
                    new_held = list(held)
                    with_line = st.lineno
                    for item in st.items:
                        scan_exprs(item.context_expr, held)
                        cid = self._resolve_lock(scope, item.context_expr)
                        if cid is None:
                            continue
                        self._note_acquire(scope, fn, cid, new_held,
                                           with_line, def_line)
                        new_held.append((cid, with_line))
                    visit(st.body, new_held)
                    continue
                # expressions hanging directly off this statement
                for field, value in ast.iter_fields(st):
                    if isinstance(value, ast.expr):
                        scan_exprs(value, held)
                    elif isinstance(value, list):
                        for v in value:
                            if isinstance(v, ast.expr):
                                scan_exprs(v, held)
                # nested statement lists (if/for/try/while bodies)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if isinstance(sub, list) and sub and \
                            isinstance(sub[0], ast.stmt):
                        visit(sub, held)
                for h in getattr(st, "handlers", []):
                    visit(h.body, held)

        visit(fn.node.body, [])

    def _note_acquire(self, scope, fn, cid, held, line, def_line):
        held_ids = [c for c, _ in held]
        if cid in held_ids and cid not in scope.rlocks:
            self.diags.append(_d(
                "L103",
                f"lock '{cid}' acquired at line {line} while already "
                "held on this path (non-reentrant: self-deadlock)",
                self.where(line),
                hint="split the critical section or use the *_locked "
                     "convention"))
        for h, _hl in held:
            if h == cid:
                continue
            if self.directives.allows("allow-lock-order", line, def_line):
                continue
            self.edges.setdefault((h, cid), (self.where(line),
                                             (line, def_line)))

    def _check_call(self, scope, fn, node: ast.Call, held, def_line):
        held_ids = [c for c, _ in held]
        lines = [node.lineno, def_line] + [hl for _, hl in held]
        callee = self._self_callee(node)
        if callee and callee in scope.fns:
            summ = self._closure(scope, callee)
            for cid in summ.acquires:
                if cid in held_ids and cid not in scope.rlocks:
                    self.diags.append(_d(
                        "L103",
                        f"call to self.{callee}() at line {node.lineno} "
                        f"re-acquires held lock '{cid}'",
                        self.where(node.lineno)))
                elif cid not in held_ids:
                    if not self.directives.allows("allow-lock-order",
                                                  *lines):
                        for h in held_ids:
                            self.edges.setdefault(
                                (h, cid),
                                (self.where(node.lineno),
                                 tuple(lines)))
            if summ.blocking and not self.directives.allows(
                    "allow-blocking", *lines):
                self.diags.append(_d(
                    "L102",
                    f"self.{callee}() blocks (transitively) while "
                    f"holding {held_ids}",
                    self.where(node.lineno),
                    hint="move the blocking work outside the lock, or "
                         "annotate '# lint: allow-blocking' if vetted"))
            return
        if self._is_blocking_call(scope, node, held=held_ids):
            if not self.directives.allows("allow-blocking", *lines):
                call_txt = _expr_text(node.func) or "<call>"
                self.diags.append(_d(
                    "L102",
                    f"blocking call {call_txt}() while holding "
                    f"{held_ids}",
                    self.where(node.lineno),
                    hint="a peer needing this lock parks behind network/"
                         "sleep time; move the call outside the lock or "
                         "annotate '# lint: allow-blocking' if vetted"))

    def _closure(self, scope: _Scope, name: str,
                 _seen: Optional[Set[str]] = None) -> _FnSummary:
        """Transitive acquires/blocking over the same-scope call graph."""
        _seen = _seen or set()
        fn = scope.fns[name]
        if name in _seen:
            return fn
        _seen.add(name)
        out = _FnSummary(fn.name, fn.node)
        out.acquires |= fn.acquires
        out.blocking = fn.blocking
        for callee in fn.calls:
            if callee in scope.fns:
                sub = self._closure(scope, callee, _seen)
                out.acquires |= sub.acquires
                out.blocking = out.blocking or sub.blocking
        return out

    # --- entry ----------------------------------------------------------
    def run(self) -> None:
        try:
            tree = ast.parse(self.src, filename=self.filename)
        except SyntaxError as e:
            self.diags.append(_d("L101", f"unparseable source: {e}",
                                 self.where(getattr(e, "lineno", 0) or 0)))
            return
        mod_scope = _Scope(self.short)
        top_fns = [n for n in tree.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        self._scan_locks(mod_scope, tree.body, self_name="<module>")
        for n in top_fns:
            mod_scope.fns[n.name] = _FnSummary(n.name, n)
        for fn in mod_scope.fns.values():
            self._summarize(mod_scope, fn)
        for fn in mod_scope.fns.values():
            self._walk_fn(mod_scope, fn)

        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            scope = _Scope(f"{self.short}.{cls.name}")
            scope.locks.update(mod_scope.locks)  # module locks visible
            scope.rlocks |= mod_scope.rlocks
            scope.conditions |= mod_scope.conditions
            self._scan_locks(scope, cls.body, self_name="self")
            for n in cls.body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scope.fns[n.name] = _FnSummary(n.name, n)
            for fn in scope.fns.values():
                self._summarize(scope, fn)
            for fn in scope.fns.values():
                self._walk_fn(scope, fn)


def _short(lock_id: str) -> str:
    return lock_id.rsplit(".", 1)[-1]


def _check_order(edges, decls) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    # declared-order violations (short-name matching)
    for (a, b), (where, _) in edges.items():
        for (x, y) in decls:
            if _short(a) == y and _short(b) == x:
                diags.append(_d(
                    "L101",
                    f"lock order violation: '{a}' acquired before '{b}' "
                    f"but '# lint: lock-order({x}<{y})' declares the "
                    "opposite",
                    where))
    # cycles
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    state: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(u):
        state[u] = 1
        stack.append(u)
        for v in graph.get(u, ()):
            if state.get(v, 0) == 0:
                cyc = dfs(v)
                if cyc:
                    return cyc
            elif state.get(v) == 1:
                return stack[stack.index(v):] + [v]
        stack.pop()
        state[u] = 2
        return None

    for u in list(graph):
        if state.get(u, 0) == 0:
            cyc = dfs(u)
            if cyc:
                where = edges.get((cyc[0], cyc[1]), ("", ()))[0]
                diags.append(_d(
                    "L101",
                    "lock-order cycle: " + " -> ".join(cyc),
                    where,
                    hint="two paths take these locks in opposite order "
                         "— pick one order and declare it with "
                         "'# lint: lock-order(a<b)'"))
                break
    return diags


def lint_source(src: str, filename: str = "<src>") -> List[Diagnostic]:
    """Lint one source string (unit tests / selftest)."""
    lint = _Lint(filename, src)
    lint.run()
    return lint.diags + _check_order(lint.edges,
                                     lint.directives.order_decls)


def iter_py_files(paths) -> List[str]:
    """Every .py file under `paths` (files or directories), sorted and
    deduped, __pycache__ skipped — the one walk both concurrency lints
    (this pass and guards.py) share."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files += [os.path.join(root, n) for n in sorted(names)
                          if n.endswith(".py")]
        elif p.endswith(".py"):
            files.append(p)
    return sorted(set(files))


def lint_paths(paths) -> List[Diagnostic]:
    """Lint every .py file under `paths` (files or directories); the
    lock-order graph is global across all of them."""
    diags: List[Diagnostic] = []
    edges: Dict[Tuple[str, str], Tuple[str, Tuple[int, ...]]] = {}
    decls: List[Tuple[str, str]] = []
    for f in iter_py_files(paths):
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        lint = _Lint(os.path.relpath(f), src)
        lint.run()
        diags += lint.diags
        for k, v in lint.edges.items():
            edges.setdefault(k, v)
        decls += lint.directives.order_decls
    return diags + _check_order(edges, decls)


def default_lint_paths(repo_root: Optional[str] = None) -> List[str]:
    root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pkg = os.path.join(root, "paddle_tpu")
    return [os.path.join(pkg, "distributed"),
            os.path.join(pkg, "observability"),
            os.path.join(pkg, "serving"),
            os.path.join(pkg, "autotune"),
            os.path.join(pkg, "fleet"),
            os.path.join(pkg, "checkpoint"),
            os.path.join(pkg, "mesh")]
