"""One synthetic bad input per diagnostic code.

`CASES` maps every implemented code to a zero-arg callable that builds
the minimal bad Program / source snippet / registry universe, runs the
owning pass, and returns its diagnostics. The CLI's ``--selftest``
asserts each case actually fires its code (a pass whose detector rots
stops being trusted the day it rots, not the day a real bug slips by);
tests/test_static_analysis.py parametrizes over the same registry so
each code is also exercised as a unit test."""
from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from .diagnostics import Diagnostic


def _mk_program(var_specs, ops):
    """Hand-assemble a Program from raw descs, BYPASSING build-time
    shape inference — exactly how a buggy transpiler or a desc edit
    corrupts a graph.

    var_specs: name -> dict(shape=..., dtype=..., persistable=...)
    ops: (type, inputs, outputs, attrs) tuples appended verbatim."""
    from paddle_tpu.fluid.framework import Operator, Program
    from paddle_tpu.fluid.proto import OpDesc

    prog = Program()
    block = prog.global_block()
    for name, spec in var_specs.items():
        block.create_var(name=name, **spec)
    for (t, ins, outs, attrs) in ops:
        op = Operator.__new__(Operator)
        op.block = block
        op.desc = OpDesc(type=t, inputs=dict(ins or {}),
                         outputs=dict(outs or {}), attrs=dict(attrs or {}))
        block.ops.append(op)
    return prog


def _verify(prog, **kw) -> List[Diagnostic]:
    from .verify import verify_program

    return verify_program(prog, **kw)


# --- verifier cases ----------------------------------------------------

def case_v001():
    # 't' is read by the first op but only produced by the second
    prog = _mk_program(
        {"a": dict(shape=[2], dtype="float32"),
         "t": dict(shape=[2], dtype="float32"),
         "b": dict(shape=[2], dtype="float32")},
        [("relu", {"X": ["t"]}, {"Out": ["b"]}, {}),
         ("relu", {"X": ["a"]}, {"Out": ["t"]}, {})],
    )
    return _verify(prog, check_shapes=False)


def case_v002():
    prog = _mk_program(
        {"a": dict(shape=[2], dtype="float32")},
        [("relu", {"X": ["ghost"]}, {"Out": ["a"]}, {})],
    )
    return _verify(prog, check_shapes=False)


def case_v003():
    # declared output shape contradicts the emitter's abstract eval
    prog = _mk_program(
        {"a": dict(shape=[2, 3], dtype="float32"),
         "b": dict(shape=[9, 9], dtype="float32")},
        [("relu", {"X": ["a"]}, {"Out": ["b"]}, {})],
    )
    return _verify(prog, check_shapes=True)


def case_v004():
    prog = _mk_program(
        {"a": dict(shape=[2, 3], dtype="float32"),
         "b": dict(shape=[2, 3], dtype="int64")},
        [("relu", {"X": ["a"]}, {"Out": ["b"]}, {})],
    )
    return _verify(prog, check_shapes=True)


def case_v005():
    prog = _mk_program(
        {"x@GRAD": dict(shape=[2], dtype="float32")},
        [],
    )
    return _verify(prog, check_shapes=False)


def case_v006():
    # 'dead' is computed and never consumed
    prog = _mk_program(
        {"a": dict(shape=[2], dtype="float32"),
         "dead": dict(shape=[2], dtype="float32")},
        [("relu", {"X": ["a"]}, {"Out": ["dead"]}, {})],
    )
    return _verify(prog, check_shapes=False)


def case_v007():
    prog = _mk_program(
        {"a": dict(shape=[2], dtype="float32"),
         "b": dict(shape=[2], dtype="float32"),
         "t": dict(shape=[2], dtype="float32")},
        [("relu", {"X": ["a"]}, {"Out": ["t"]}, {}),
         ("relu", {"X": ["b"]}, {"Out": ["t"]}, {}),
         ("relu", {"X": ["t"]}, {"Out": ["a"]}, {})],
    )
    return _verify(prog, check_shapes=False)


def case_v008():
    prog = _mk_program(
        {"c": dict(shape=[1], dtype="bool")},
        [("conditional_block", {"Cond": ["c"]}, {},
          {"sub_block": 99})],
    )
    return _verify(prog, check_shapes=False)


def case_v009():
    prog = _mk_program(
        {"a": dict(shape=[2], dtype="float32")},
        [("totally_bogus_op", {"X": ["a"]}, {"Out": ["a"]}, {})],
    )
    return _verify(prog, check_shapes=False)


def case_v010():
    # synthetic reuse log: 'buf' is merged into at op 0 while its
    # storage is still used at op 2
    from paddle_tpu.fluid.memory_optimization_transpiler import (
        ControlFlowGraph,
    )
    from .verify import check_reuse_events

    prog = _mk_program(
        {"a": dict(shape=[2], dtype="float32"),
         "buf": dict(shape=[2], dtype="float32"),
         "out": dict(shape=[2], dtype="float32"),
         "z": dict(shape=[2], dtype="float32")},
        [("relu", {"X": ["a"]}, {"Out": ["out"]}, {}),
         ("relu", {"X": ["buf"]}, {"Out": ["z"]}, {}),
         ("relu", {"X": ["buf"]}, {"Out": ["z"]}, {})],
    )
    cfg = ControlFlowGraph(prog.global_block())
    return check_reuse_events(cfg, [(0, "out", "buf")])


# --- concurrency-lint cases -------------------------------------------

_L101_SRC = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
'''

_L102_SRC = '''
import threading

class S:
    def __init__(self, sock):
        self._mu = threading.Lock()
        self._sock = sock

    def pull(self):
        with self._mu:
            return self._sock.recv(4096)
'''

_L103_SRC = '''
import threading

class S:
    def __init__(self):
        self._mu = threading.Lock()

    def outer(self):
        with self._mu:
            self.inner()

    def inner(self):
        with self._mu:
            pass
'''


def case_l101():
    from .locks import lint_source

    return lint_source(_L101_SRC, "snippet_l101.py")


def case_l102():
    from .locks import lint_source

    return lint_source(_L102_SRC, "snippet_l102.py")


def case_l103():
    from .locks import lint_source

    return lint_source(_L103_SRC, "snippet_l103.py")


# --- guards-lint cases --------------------------------------------------

# a thread-entry (Thread target) and a public method share _n; one
# access (the scheduler's write) skips the majority guard
_L104_SRC = '''
import threading

class S:
    def __init__(self):
        self._mu = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._loop)

    def read(self):
        with self._mu:
            return self._n

    def bump(self):
        with self._mu:
            self._n += 1

    def _loop(self):
        self._n = 0
'''

# declared guard: every unguarded access fires even without a majority
_L104_DECL_SRC = '''
import threading

class S:
    def __init__(self):
        self._mu = threading.Lock()
        self._q = []  # guarded-by: _mu

    def put(self, x):
        self._q.append(x)
'''

_L105_SRC = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._n = 0

    def one(self):
        with self._a:
            self._n += 1

    def two(self):
        with self._b:
            self._n += 1
'''

# the PR 5/6 double-answer shape: a guarded read, the lock released,
# and the dependent write re-acquiring it
_L106_SRC = '''
import threading

class S:
    def __init__(self):
        self._mu = threading.Lock()
        self._n = 0  # guarded-by: _mu

    def bump(self):
        with self._mu:
            seen = self._n
        with self._mu:
            self._n = seen + 1
'''


def case_l104():
    from .guards import lint_source

    diags = lint_source(_L104_SRC, "snippet_l104.py")
    # the declared-guard form must fire too — inference and declaration
    # are both load-bearing, so the case covers both or fails
    diags += lint_source(_L104_DECL_SRC, "snippet_l104_decl.py")
    if sum(1 for d in diags if d.code == "L104") < 2:
        raise AssertionError(
            "L104 must fire for BOTH the inferred and the declared "
            f"guard: {[d.format() for d in diags]}")
    return diags


def case_l105():
    from .guards import lint_source

    return lint_source(_L105_SRC, "snippet_l105.py")


def case_l106():
    from .guards import lint_source

    return lint_source(_L106_SRC, "snippet_l106.py")


# --- invariant-lint cases ---------------------------------------------

def case_n201():
    from .invariants import check_fault_sites

    # exact names plus f-string wildcard FAMILIES — `serving.*` is the
    # real one serving/server.py declares via `fire(f"serving.{method}")`
    declared = ({"connect", "master.snapshot"},
                {"recv.*", "send.*", "serving.*"})
    used = [("nope.bogus_site", "snippet.py", 1, False),
            ("serving.infer", "snippet.py", 2, False)]
    diags = check_fault_sites(declared, used)
    # the family must CLAIM serving.infer: a second, spurious N201 here
    # means wildcard matching rotted — crash the case so it fails
    if any("serving.infer" in d.message for d in diags):
        raise AssertionError(
            "wildcard site family 'serving.*' did not match "
            "'serving.infer'")
    return diags


def case_n202():
    from .invariants import NameUniverse, check_names

    universe = NameUniverse(({"rpc.client.retries"}, {"rpc.server.*.ms"}),
                            (set(), set()))
    refs = [("rpc.client.bogus_metric", "snippet.py", 1, False)]
    return check_names(universe, refs)


def case_n203():
    from .invariants import check_flags

    defined = {"benchmark", "trace"}
    refs = [("not_a_flag", "snippet.py", 1, "read")]
    return check_flags(defined, refs, warn_unread=False)


def case_n204():
    from .invariants import check_flags

    defined = {"benchmark", "never_read_flag"}
    refs = [("benchmark", "snippet.py", 1, "read")]
    return check_flags(defined, refs, warn_unread=True)


def case_n205():
    from .invariants import check_versioned_gauge_source

    # a per-<model>.v<version> gauge with no .set(0) retirement site —
    # the PR 5/6 hot-swap gauge-clobber shape, mechanized
    src = '''
class Engine:
    def __init__(self, name, version):
        self._g_depth = _metrics.gauge(
            f"serving.queue_depth.{name}.v{version}")
        self._g_ok = _metrics.gauge(f"serving.live.{name}.v{version}")

    def stop(self):
        self._g_ok.set(0)
'''
    diags = check_versioned_gauge_source(src, "snippet_n205.py")
    # the zeroed gauge must NOT fire: a spurious hit here means the
    # zero-site matcher rotted — crash the case so it fails
    if any("_g_ok" in d.message for d in diags):
        raise AssertionError(
            "N205 fired on a gauge that HAS a .set(0) site")
    return diags


CASES: Dict[str, Callable[[], List[Diagnostic]]] = {
    "V001": case_v001,
    "V002": case_v002,
    "V003": case_v003,
    "V004": case_v004,
    "V005": case_v005,
    "V006": case_v006,
    "V007": case_v007,
    "V008": case_v008,
    "V009": case_v009,
    "V010": case_v010,
    "L101": case_l101,
    "L102": case_l102,
    "L103": case_l103,
    "L104": case_l104,
    "L105": case_l105,
    "L106": case_l106,
    "N201": case_n201,
    "N202": case_n202,
    "N203": case_n203,
    "N204": case_n204,
    "N205": case_n205,
}


def run_selftest() -> List[Tuple[str, bool, List[Diagnostic]]]:
    """(code, fired, diagnostics) per case. A case passes iff its own
    code appears in the diagnostics its bad input produces."""
    results = []
    for code, fn in sorted(CASES.items()):
        try:
            diags = fn()
            fired = any(d.code == code for d in diags)
        except Exception as e:  # a crashing detector is a failing case
            diags = [Diagnostic(code=code, severity="error",
                                message=f"selftest case crashed: "
                                        f"{type(e).__name__}: {e}")]
            fired = False
        results.append((code, fired, diags))
    return results
