"""`paddle.trainer_config_helpers` — the reference's legacy config DSL
surface (reference python/paddle/trainer_config_helpers/{layers,networks,
activations,poolings,attrs,optimizers,math}.py), mapped onto the v2 layer
functions so the reference's OWN config files execute unmodified:

    sys.modules['paddle.trainer_config_helpers'] = this module
    exec(open('tests/configs/projections.py').read())

Differences from the reference module (all by design):
  - layers BUILD into the implicit fluid default program (and actually
    execute); the reference only parsed them into a config proto.
  - `settings()` records the optimization config for the caller to apply
    (tests attach a fluid optimizer from it); it configures nothing
    globally.
  - `ExtraAttr(drop_rate=r)` wraps the layer output in dropout;
    error_clipping_threshold is recorded but clipping is applied at
    optimize time by fluid.clip (the TPU-era placement).
  - data layer sequence-ness/int-ness comes from `declare_input_types`
    (the role the reference's DataProvider declaration played — config
    files never carried it either).
"""
from __future__ import annotations

import functools
import inspect

from ..fluid import layers as _fl
from ..fluid.initializer import (ConstantInitializer, NormalInitializer,
                                 UniformInitializer)
from ..fluid.param_attr import ParamAttr as _FluidParamAttr
from ..v2 import layer as _v2l
from ..v2 import networks as _v2n
from ..v2.layer import _Act, _MixedBuilder, _Pool, _Projection

# --- module state the harness reads back -----------------------------------

_settings: dict = {}
_outputs: list = []
_data_layers: list = []  # (name, var, kind) in declaration order
_input_types: dict = {}  # name -> 'dense'|'int'|'seq'|'int_seq'
_fixed_batch: list = []  # [N] when data layers should pin the batch dim


def reset():
    """Clear recorded state between config files (harness hook)."""
    _settings.clear()
    del _outputs[:]
    del _data_layers[:]
    _input_types.clear()
    del _fixed_batch[:]


def set_fixed_batch(n):
    """Pin the batch dimension of subsequent data layers (harness hook,
    for configs whose graphs make downstream widths batch-dependent —
    e.g. trans_layer's batch-matrix transpose feeding an fc)."""
    del _fixed_batch[:]
    if n:
        _fixed_batch.append(int(n))


def declare_input_types(types: dict):
    """Declare per-data-layer runtime types ('dense'|'int'|'seq'|
    'int_seq'), the way the reference's DataProvider declared them
    (trainer/PyDataProvider2 input_types) — configs never carried this."""
    _input_types.update(types)


def get_config():
    return {"settings": dict(_settings), "outputs": list(_outputs),
            "data_layers": list(_data_layers)}


def settings(**kwargs):
    """reference trainer_config_helpers/optimizers.py settings()."""
    _settings.update(kwargs)


def outputs(*layers):
    for group in layers:
        vs = group if isinstance(group, (list, tuple)) else [group]
        for v in vs:
            _outputs.append(v.to_variable()
                            if isinstance(v, _MixedBuilder) else v)


# --- attribute / activation / pooling classes ------------------------------


def ParamAttr(name=None, initial_max=None, initial_min=None,
              initial_mean=None, initial_std=None, learning_rate=1.0,
              l1_rate=None, l2_rate=None, is_static=False, **kwargs):
    """reference attrs.ParameterAttribute -> fluid ParamAttr. initial_max/
    min pick a uniform initializer, initial_mean/std a gaussian (the
    reference's parameter_config translation)."""
    init = None
    if initial_max is not None or initial_min is not None:
        lo = initial_min if initial_min is not None else -(initial_max or 0)
        hi = initial_max if initial_max is not None else -(initial_min or 0)
        init = UniformInitializer(low=float(lo), high=float(hi))
    elif initial_std is not None or initial_mean is not None:
        mean = float(initial_mean or 0.0)
        std = float(initial_std if initial_std is not None else 0.01)
        init = (ConstantInitializer(mean) if std == 0.0
                else NormalInitializer(loc=mean, scale=std))
    reg = None
    if l2_rate:
        from ..fluid.regularizer import L2Decay
        reg = L2Decay(float(l2_rate))
    elif l1_rate:
        from ..fluid.regularizer import L1Decay
        reg = L1Decay(float(l1_rate))
    return _FluidParamAttr(name=name, initializer=init,
                           learning_rate=float(learning_rate),
                           regularizer=reg, trainable=not is_static)


ParameterAttribute = ParamAttr


class ExtraLayerAttribute:
    """reference attrs.ExtraLayerAttribute: per-layer extras. drop_rate
    is applied (dropout on the layer output); error_clipping_threshold is
    recorded for fluid.clip at optimize time; device is meaningless here
    (placement belongs to XLA/GSPMD) and ignored."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None, **kwargs):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate


ExtraAttr = ExtraLayerAttribute


def _act_class(name):
    class _ActFactory:
        def __new__(cls):
            return _Act(name)

    _ActFactory.__name__ = (name or "linear").title() + "Activation"
    return _ActFactory


LinearActivation = _act_class(None)
IdentityActivation = _act_class(None)
ReluActivation = _act_class("relu")
SigmoidActivation = _act_class("sigmoid")
TanhActivation = _act_class("tanh")
SoftmaxActivation = _act_class("softmax")
ExpActivation = _act_class("exp")
SquareActivation = _act_class("square")
AbsActivation = _act_class("abs")
LogActivation = _act_class("log")
SoftReluActivation = _act_class("softplus")
BReluActivation = _act_class("brelu")
STanhActivation = _act_class("stanh")


def _pool_class(kind, name):
    class _PoolFactory:
        def __new__(cls, **kwargs):
            return _Pool(kind)

    _PoolFactory.__name__ = name
    return _PoolFactory


MaxPooling = _pool_class("max", "MaxPooling")
AvgPooling = _pool_class("average", "AvgPooling")
SumPooling = _pool_class("sum", "SumPooling")
SquareRootNPooling = _pool_class("sqrt", "SquareRootNPooling")
CudnnMaxPooling = MaxPooling
CudnnAvgPooling = AvgPooling


class AggregateLevel:
    """reference layers.AggregateLevel; the padded+lengths sequence model
    is single-level, so both levels aggregate the one time axis."""
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    FROM_NO_SEQUENCE = "non-seq"
    FROM_TIMESTEP = "non-seq"
    FROM_SEQUENCE = "seq"


class layer_math:
    """reference trainer_config_helpers/math.py (paddle.v2.layer_math):
    elementwise math over layers; operator overloads live on Variable
    (fluid/layers/math_op_patch.py)."""

    @staticmethod
    def exp(x):
        return _fl.exp(_resolve(x))

    @staticmethod
    def log(x):
        return _fl.log(_resolve(x))

    @staticmethod
    def abs(x):
        return _fl.abs(_resolve(x))

    @staticmethod
    def sigmoid(x):
        return _fl.sigmoid(_resolve(x))

    @staticmethod
    def tanh(x):
        return _fl.tanh(_resolve(x))

    @staticmethod
    def square(x):
        return _fl.square(_resolve(x))

    @staticmethod
    def relu(x):
        return _fl.relu(_resolve(x))

    @staticmethod
    def sqrt(x):
        return _fl.sqrt(_resolve(x))

    @staticmethod
    def reciprocal(x):
        return _fl.reciprocal(_resolve(x))


# --- layer functions: v2.layer/networks wrapped for shim semantics ---------


def _resolve(v):
    return v.to_variable() if isinstance(v, _MixedBuilder) else v


def _resolve_tree(v):
    if isinstance(v, _MixedBuilder):
        return v.to_variable()
    if isinstance(v, (list, tuple)):
        return type(v)(_resolve_tree(x) for x in v)
    return v


def _wrap(fn):
    @functools.wraps(fn)
    def impl(*args, **kwargs):
        layer_attr = kwargs.pop("layer_attr", None)
        args = tuple(_resolve_tree(a) for a in args)
        kwargs = {k: _resolve_tree(v) for k, v in kwargs.items()}
        out = fn(*args, **kwargs)
        if isinstance(layer_attr, ExtraLayerAttribute) and \
                layer_attr.drop_rate and not isinstance(
                    out, (_Projection, _MixedBuilder, list, tuple)):
            out = _fl.dropout(out, dropout_prob=float(layer_attr.drop_rate))
        return out

    return impl


def data_layer(name, size, height=None, width=None, depth=None, **kwargs):
    """reference layers.data_layer — runtime type (sequence-ness,
    integer-ness) comes from declare_input_types, as it came from the
    DataProvider in the reference."""
    kind = _input_types.get(name, "dense")
    t = {"dense": _v2l.data_type.dense_vector(size),
         "int": _v2l.data_type.integer_value(size),
         "seq": _v2l.data_type.dense_vector_sequence(size),
         "int_seq": _v2l.data_type.integer_value_sequence(size)}[kind]
    lod = {"seq": 1, "int_seq": 1}.get(kind, 0)
    if _fixed_batch and kind == "dense":
        var = _fl.data(name=name, shape=[_fixed_batch[0], size],
                       dtype="float32", append_batch_size=False)
        var._v2_type = t
    else:
        var = _v2l.data(name, t, lod_level=lod) if lod else _v2l.data(name, t)
    if height and width:
        var._img_hw = (int(height), int(width))
        if depth:
            var._img_dhw = (int(depth), int(height), int(width))
    _data_layers.append((name, var, kind))
    return var


def dropout_layer(input, dropout_rate=0.5, **kwargs):
    return _fl.dropout(_resolve(input), dropout_prob=float(dropout_rate))


def define_py_data_sources2(*args, **kwargs):
    """reference trainer/config_parser data-source declaration: a training
    harness concern; paddle_tpu feeds through reader/DataFeeder instead."""
    raise NotImplementedError(
        "define_py_data_sources2 configures the legacy DataProvider; "
        "paddle_tpu feeds data through paddle_tpu.reader / DataFeeder")


def _export_v2():
    """Every public callable from v2.layer + v2.networks, shim-wrapped."""
    g = globals()
    for mod in (_v2l, _v2n):
        for nm, obj in vars(mod).items():
            if nm.startswith("_") or nm in g:
                continue
            if inspect.isfunction(obj):
                g[nm] = _wrap(obj)
            elif inspect.isclass(obj) or isinstance(obj, type):
                g[nm] = obj
    # classes/objects the configs reference directly
    g.setdefault("StaticInput", _v2l.StaticInput)
    # nested-sequence input marker: the padded+lengths model flattens
    # 2-level LoD before the graph (SURVEY §5.7 / v2/layer.py module
    # docstring), so inside a config a SubsequenceInput behaves as the
    # flattened one-level sequence it arrives as
    g.setdefault("SubsequenceInput", lambda input, **kw: input)


_export_v2()

# shim-local definitions shadow the generic export where semantics differ
mixed_layer = _wrap(_v2l.mixed_layer)
memory = _v2l.memory  # must run inside the step fn, unwrapped
