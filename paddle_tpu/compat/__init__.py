"""Source-level compatibility shims for the reference's legacy config
surfaces. `trainer_config_helpers` lets the reference's own DSL config
files (python/paddle/trainer_config_helpers/tests/configs/*.py) run
unmodified against paddle_tpu (see tests/test_reference_configs.py)."""
from . import trainer_config_helpers  # noqa: F401
