"""Training-curve plotting (reference python/paddle/v2/plot/plot.py Ploter —
the v2 notebook workflow's live loss/metric curves).

Same API: `Ploter("train cost", "test cost")`, `append(title, step, value)`,
`plot(path=None)`, `reset()`. Differences by design:

- headless-first: with DISABLE_PLOT=True (or no matplotlib) the plot() call
  degrades to a one-line text summary per series instead of crashing, so
  event handlers are portable between notebooks and batch TPU jobs;
- series data is exposed (`data(title)` -> (steps, values)) for tests and
  for exporting curves to the profiler/metrics pipeline.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

__all__ = ["PlotData", "Ploter"]


class PlotData:
    def __init__(self):
        self.step: List[int] = []
        self.value: List[float] = []

    def append(self, step: int, value: float) -> None:
        self.step.append(step)
        self.value.append(value)

    def reset(self) -> None:
        self.step = []
        self.value = []


def _have_matplotlib() -> bool:
    try:
        import matplotlib  # noqa: F401
        return True
    except ImportError:
        return False


def _plotting_disabled() -> bool:
    return os.environ.get("DISABLE_PLOT") == "True" or not _have_matplotlib()


class Ploter:
    def __init__(self, *titles: str):
        self._titles = titles
        self._series: Dict[str, PlotData] = {t: PlotData() for t in titles}

    def append(self, title: str, step: int, value: float) -> None:
        if title not in self._series:
            raise KeyError(f"unknown series '{title}' — declared: "
                           f"{list(self._titles)}")
        self._series[title].append(step, float(value))

    def data(self, title: str) -> Tuple[List[int], List[float]]:
        d = self._series[title]
        return d.step, d.value

    def plot(self, path: Optional[str] = None) -> None:
        # an explicit path means "write the file": only a genuinely missing
        # matplotlib prevents that (Agg needs no display, so DISABLE_PLOT
        # only suppresses the interactive/no-path mode)
        if _plotting_disabled() and (path is None or not _have_matplotlib()):
            if path is not None:
                print(f"[plot] matplotlib unavailable — NOT writing {path}")
            for t in self._titles:
                d = self._series[t]
                if d.step:
                    print(f"[plot] {t}: step {d.step[-1]} "
                          f"value {d.value[-1]:.6g} ({len(d.step)} points)")
            return
        if path is not None:
            # File output renders through an explicit Agg canvas, bypassing
            # the process-global backend entirely — a pyplot import earlier
            # in the process (with any backend) can't break savefig.
            from matplotlib.backends.backend_agg import FigureCanvasAgg
            from matplotlib.figure import Figure

            fig = Figure()
            FigureCanvasAgg(fig)
            self._draw(fig.add_subplot(111))
            fig.savefig(path)
            return
        import matplotlib.pyplot as plt

        self._draw(plt)
        try:
            from IPython import display
            display.clear_output(wait=True)
            display.display(plt.gcf())
        except ImportError:
            plt.show()
        plt.gcf().clear()

    def _draw(self, ax) -> None:
        """Plot all non-empty series onto `ax` (an Axes or the pyplot
        module — both expose plot/legend)."""
        drawn = []
        for t in self._titles:
            d = self._series[t]
            if d.step:
                ax.plot(d.step, d.value)
                drawn.append(t)
        ax.legend(drawn, loc="upper left")

    def reset(self) -> None:
        for d in self._series.values():
            d.reset()
