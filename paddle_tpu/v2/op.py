"""reference python/paddle/v2/op.py: arithmetic sugar over layers —
add/sub/mul/neg between layer outputs (and scalars) via the elementwise
and slope_intercept ops, exactly the operator set the reference
monkey-patched onto LayerOutput."""
from ..fluid import layers as _fl


def add(a, b):
    if isinstance(b, (int, float)):
        return _fl.scale(a, scale=1.0, bias=float(b))
    return _fl.elementwise_add(a, b)


def sub(a, b):
    if isinstance(b, (int, float)):
        return _fl.scale(a, scale=1.0, bias=-float(b))
    return _fl.elementwise_sub(a, b)


def neg(a):
    return _fl.scale(a, scale=-1.0)


def mul(a, b):
    if isinstance(b, (int, float)):
        return _fl.scale(a, scale=float(b))
    return _fl.elementwise_mul(a, b)
