"""v2-compatible API facade (reference python/paddle/v2/ — the 2016-era
event-loop framework: trainer.py SGD:37/train:137, layer.py, parameters.py,
inference.py).

Capability, not code, parity (SURVEY.md §2.8/§7 step 10): v2-style programs
— build a cost layer, create parameters, run an event-handler training loop
— execute on the fluid-equivalent TPU core underneath (one Program, XLA
lowering). The layer DSL maps onto fluid layers."""
from .. import batch, reader  # noqa: F401
from .. import dataset  # noqa: F401
from . import (  # noqa: F401
    attr, data_feeder, evaluator, event, image, layer, minibatch, networks,
    op, optimizer, plot, topology,
)
from .layer import activation, data_type, pooling  # noqa: F401
from .topology import Topology  # noqa: F401
from .inference import infer  # noqa: F401
from .parameters import Parameters, create  # noqa: F401
from .trainer import SGD  # noqa: F401


def init(use_gpu: bool = False, trainer_count: int = 1, **kwargs):
    """reference paddle.init — device selection is automatic under JAX;
    kept as a no-op for source compatibility."""
    return None
