"""reference python/paddle/v2/attr.py: parameter/extra attribute aliases
over the fluid ParamAttr machinery."""
from ..fluid.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

Param = ParamAttr
ParameterAttribute = ParamAttr


class ExtraAttr:
    """reference ExtraLayerAttribute — accepted for source compatibility;
    drop_rate maps to dropout at the layer level, the rest (device
    placement, error clipping thresholds) are superseded by mesh
    placement and fluid.clip."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None, **kwargs):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


ExtraLayerAttribute = ExtraAttr
