"""reference python/paddle/v2/data_feeder.py — the v2 DataFeeder is the
fluid DataFeeder (ragged reader rows -> padded+lengths feed dicts)."""
from ..fluid.data_feeder import DataFeeder  # noqa: F401
