"""reference python/paddle/v2/minibatch.py — re-exports the package-level
batch() combinator."""
from .. import batch  # noqa: F401
