"""v2 SGD trainer: the event-handler training loop (reference
python/paddle/v2/trainer.py SGD:37, train:137 — train_one_pass firing
BeginPass/BeginIteration/EndIteration/EndPass events)."""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from .. import fluid
from . import event as v2_event


def _data_var_names(block):
    """Feed placeholders in declaration order: vars that are read but never
    produced by any op and not persistable (layers.data creates these)."""
    produced = set()
    used = set()
    for op in block.ops:
        produced.update(op.desc.output_names())
        used.update(op.desc.input_names())
    return [
        n for n, v in block.vars.items()
        if n in used and n not in produced and not v.persistable
        # @LEN lengths companions ride along with their padded var — the
        # DataFeeder emits both from the one ragged sample slot
        and not n.endswith("@LEN")
    ]


class SGD:
    def __init__(self, cost, parameters, update_equation,
                 extra_layers=None, is_local: bool = True):
        from .optimizer import _V2Optimizer

        self.cost = cost
        self.parameters = parameters
        if isinstance(update_equation, _V2Optimizer):
            update_equation = update_equation.fluid_opt
        self._optimizer = update_equation
        self._main = parameters.main_program
        # snapshot the forward-only program BEFORE minimize appends
        # backward+optimize ops — test() must never update parameters
        self._test_prog = self._main.clone(for_test=True)
        # minimize appends backward+optimize ops once, at trainer creation
        # (the reference compiles the GradientMachine here). It also adds
        # optimizer accumulators to the startup program, which parameters
        # .create() already executed — run just the new init ops.
        from ..fluid.framework import program_guard

        with program_guard(self._main, parameters.startup_program):
            self._optimizer.minimize(self.cost)
        self._exe = fluid.Executor()
        self._init_missing_vars()

    def _init_missing_vars(self):
        scope = self.parameters.scope
        startup = self.parameters.startup_program
        block = startup.global_block()
        if all(scope.has_var(o) for op in block.ops
               for o in op.desc.output_names()):
            return
        pruned = startup.clone()
        # clone preserves op order — keep (positionally) only the ops whose
        # outputs aren't in scope yet
        pruned.global_block().ops = [
            cop for cop, orig in zip(pruned.global_block().ops, block.ops)
            if any(not scope.has_var(o) for o in orig.desc.output_names())
        ]
        with fluid.scope_guard(scope):
            self._exe.run(pruned)

    def _feeder(self, feeding: Optional[Dict[str, int]]):
        block = self._main.global_block()
        if feeding is None:
            # feed order = declaration order of data vars (consumed but
            # never produced, non-persistable)
            names = _data_var_names(block)
        else:
            names = [n for n, _ in sorted(feeding.items(),
                                          key=lambda kv: kv[1])]
        feed_list = [block.var(n) for n in names]
        return fluid.DataFeeder(place=None, feed_list=feed_list)

    def train(self, reader, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              feeding: Optional[Dict[str, int]] = None):
        """reader yields minibatches (lists of samples). Fires v2 events."""
        event_handler = event_handler or (lambda e: None)
        feeder = self._feeder(feeding)
        with fluid.scope_guard(self.parameters.scope):
            for pass_id in range(num_passes):
                event_handler(v2_event.BeginPass(pass_id))
                for batch_id, data in enumerate(reader()):
                    event_handler(v2_event.BeginIteration(pass_id, batch_id))
                    (loss,) = self._exe.run(
                        self._main, feed=feeder.feed(data),
                        fetch_list=[self.cost],
                    )
                    event_handler(v2_event.EndIteration(
                        pass_id, batch_id, float(np.asarray(loss).ravel()[0])
                    ))
                event_handler(v2_event.EndPass(pass_id))

    def test(self, reader, feeding: Optional[Dict[str, int]] = None):
        feeder = self._feeder(feeding)
        costs = []
        with fluid.scope_guard(self.parameters.scope):
            for data in reader():
                (loss,) = self._exe.run(self._test_prog,
                                        feed=feeder.feed(data),
                                        fetch_list=[self.cost])
                costs.append(float(np.asarray(loss).ravel()[0]))
        return v2_event.TestResult(float(np.mean(costs)) if costs else 0.0)
