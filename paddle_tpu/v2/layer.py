"""v2 layer DSL mapped onto fluid layers (reference python/paddle/v2/layer.py
+ trainer_config_helpers/layers.py — declarative layers composed by passing
outputs as inputs). Each function appends ops to the implicit default
program, exactly like fluid layers; the v2-specific `data_type` objects
translate to fluid data vars."""
from __future__ import annotations

from ..fluid import layers as _fl


class _DataType:
    def __init__(self, kind: str, dim: int, seq: bool = False):
        self.kind = kind
        self.dim = dim
        self.seq = seq


class data_type:
    """reference paddle.v2.data_type."""

    @staticmethod
    def dense_vector(dim):
        return _DataType("dense", dim)

    @staticmethod
    def integer_value(dim):
        return _DataType("int", dim)

    @staticmethod
    def integer_value_sequence(dim):
        return _DataType("int", dim, seq=True)

    @staticmethod
    def dense_vector_sequence(dim):
        return _DataType("dense", dim, seq=True)


def data(name, type: _DataType, **kwargs):
    if type.kind == "int":
        shape = [1]
        dtype = "int64"
    else:
        shape = [type.dim]
        dtype = "float32"
    var = _fl.data(name=name, shape=shape, dtype=dtype, **kwargs)
    var._v2_type = type  # embedding_layer sizes its table from this
    return var


def fc_layer(input, size, act=None, **kwargs):
    return _fl.fc(input=input, size=size, act=act, **kwargs)


def embedding_layer(input, size, vocab_size=None, **kwargs):
    """Table rows come from the input data layer's declared integer dim
    (reference: the v2 config carries the vocab through the data type)."""
    if vocab_size is None:
        t = getattr(input, "_v2_type", None)
        if t is None or t.kind != "int":
            raise ValueError(
                "embedding_layer needs vocab_size= or an input created by "
                "v2.layer.data with integer_value(_sequence)(dim)"
            )
        vocab_size = t.dim
    return _fl.embedding(input, size=[vocab_size, size], **kwargs)


def mixed_layer(input, size, act=None, **kwargs):
    ins = input if isinstance(input, (list, tuple)) else [input]
    return _fl.fc(input=list(ins), size=size, act=act)


def classification_cost(input, label):
    return _fl.mean(_fl.cross_entropy(input=input, label=label))


def square_error_cost(input, label):
    return _fl.mean(_fl.square_error_cost(input=input, label=label))


def cross_entropy_cost(input, label):
    return classification_cost(input, label)


# direct fluid passthroughs under their v2 names
conv_layer = _fl.conv2d
pooling_layer = _fl.pool2d
batch_norm_layer = _fl.batch_norm
dropout_layer = _fl.dropout
concat_layer = None  # set below (needs list signature)


def _concat(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.concat(input, **kwargs)


concat_layer = _concat
