"""v2 layer DSL mapped onto fluid layers (reference python/paddle/v2/layer.py
+ trainer_config_helpers/layers.py — declarative layers composed by passing
outputs as inputs). Each function appends ops to the implicit default
program, exactly like fluid layers; the v2-specific `data_type` objects
translate to fluid data vars."""
from __future__ import annotations

from ..fluid import layers as _fl


class _DataType:
    def __init__(self, kind: str, dim: int, seq: bool = False):
        self.kind = kind
        self.dim = dim
        self.seq = seq


class data_type:
    """reference paddle.v2.data_type."""

    @staticmethod
    def dense_vector(dim):
        return _DataType("dense", dim)

    @staticmethod
    def integer_value(dim):
        return _DataType("int", dim)

    @staticmethod
    def integer_value_sequence(dim):
        return _DataType("int", dim, seq=True)

    @staticmethod
    def dense_vector_sequence(dim):
        return _DataType("dense", dim, seq=True)


def data(name, type: _DataType, **kwargs):
    if type.kind == "int":
        shape = [1]
        dtype = "int64"
    else:
        shape = [type.dim]
        dtype = "float32"
    var = _fl.data(name=name, shape=shape, dtype=dtype, **kwargs)
    var._v2_type = type  # embedding_layer sizes its table from this
    return var


def fc_layer(input, size, act=None, **kwargs):
    return _fl.fc(input=input, size=size, act=_act_name(act), **kwargs)


def embedding_layer(input, size, vocab_size=None, **kwargs):
    """Table rows come from the input data layer's declared integer dim
    (reference: the v2 config carries the vocab through the data type)."""
    if vocab_size is None:
        t = getattr(input, "_v2_type", None)
        if t is None or t.kind != "int":
            raise ValueError(
                "embedding_layer needs vocab_size= or an input created by "
                "v2.layer.data with integer_value(_sequence)(dim)"
            )
        vocab_size = t.dim
    return _fl.embedding(input, size=[vocab_size, size], **kwargs)


def mixed_layer(input, size, act=None, **kwargs):
    ins = input if isinstance(input, (list, tuple)) else [input]
    return _fl.fc(input=list(ins), size=size, act=_act_name(act))


def classification_cost(input, label):
    return _fl.mean(_fl.cross_entropy(input=input, label=label))


def square_error_cost(input, label):
    return _fl.mean(_fl.square_error_cost(input=input, label=label))


def cross_entropy_cost(input, label):
    return classification_cost(input, label)


def _concat(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.concat(input, **kwargs)


concat_layer = _concat


# --- activation / pooling namespaces (reference trainer_config_helpers
# activations.py / poolings.py: layer args take ReluActivation() /
# MaxPooling() instances) ---------------------------------------------------


class _Act:
    def __init__(self, name):
        self.name = name


class activation:
    """reference paddle.v2.activation.*"""

    Relu = staticmethod(lambda: _Act("relu"))
    Sigmoid = staticmethod(lambda: _Act("sigmoid"))
    Tanh = staticmethod(lambda: _Act("tanh"))
    Softmax = staticmethod(lambda: _Act("softmax"))
    Linear = staticmethod(lambda: _Act(None))
    Identity = staticmethod(lambda: _Act(None))


class _Pool:
    def __init__(self, kind):
        self.kind = kind


class pooling:
    """reference paddle.v2.pooling.* (sequence poolings)."""

    Max = staticmethod(lambda: _Pool("max"))
    Avg = staticmethod(lambda: _Pool("average"))
    Sum = staticmethod(lambda: _Pool("sum"))
    SquareRootN = staticmethod(lambda: _Pool("sqrt"))


def _act_name(act):
    return act.name if isinstance(act, _Act) else act


# --- sequence layers (reference trainer_config_helpers/layers.py:
# last_seq, first_seq, pooling_layer, lstmemory, grumemory, simple_lstm,
# simple_gru, expand_layer) -------------------------------------------------


def last_seq(input, **kwargs):
    return _fl.sequence_last_step(input)


def first_seq(input, **kwargs):
    return _fl.sequence_first_step(input)


def pooling_layer(input, pooling_type=None, **kwargs):
    """Sequence pooling (reference pooling_layer) — NOT image pooling
    (that's img_pool_layer)."""
    kind = pooling_type.kind if isinstance(pooling_type, _Pool) else (
        pooling_type or "max")
    return _fl.sequence_pool(input=input, pool_type=kind)


def lstmemory(input, size=None, reverse=False, act=None, **kwargs):
    """reference lstmemory: `size` is the HIDDEN width; the input must
    carry 4*size projected features (pair with fc_layer, as
    trainer_config_helpers documents). Default size = input_width // 4."""
    width = int(input.shape[-1])
    if size is None:
        size = width // 4
    if width != size * 4:
        raise ValueError(
            f"lstmemory(size={size}) needs an input of width {size * 4} "
            f"(4*size projected features), got {width}")
    h, _ = _fl.dynamic_lstm(input=input, size=size * 4, is_reverse=reverse)
    return h


def simple_lstm(input, size, reverse=False, **kwargs):
    """reference networks.simple_lstm: fc projection + lstmemory."""
    proj = _fl.fc(input=input, size=size * 4, num_flatten_dims=2)
    h, _ = _fl.dynamic_lstm(input=proj, size=size * 4, is_reverse=reverse)
    return h


def grumemory(input, size=None, reverse=False, **kwargs):
    """`size` is the hidden width; input carries 3*size projected gates."""
    width = int(input.shape[-1])
    if size is None:
        size = width // 3
    if width != size * 3:
        raise ValueError(
            f"grumemory(size={size}) needs an input of width {size * 3} "
            f"(3*size projected gates), got {width}")
    return _fl.dynamic_gru(input=input, size=size, is_reverse=reverse)


def simple_gru(input, size, reverse=False, **kwargs):
    proj = _fl.fc(input=input, size=size * 3, num_flatten_dims=2)
    return _fl.dynamic_gru(input=proj, size=size, is_reverse=reverse)


def expand_layer(input, expand_as, **kwargs):
    return _fl.sequence_expand(input, expand_as)


# --- image layers (reference img_conv_layer / img_pool_layer /
# simple_img_conv_pool) -----------------------------------------------------


def img_conv_layer(input, filter_size, num_filters, stride=1, padding=0,
                   act=None, **kwargs):
    return _fl.conv2d(input=input, num_filters=num_filters,
                      filter_size=filter_size, stride=stride,
                      padding=padding, act=_act_name(act))


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   **kwargs):
    kind = pool_type.kind if isinstance(pool_type, _Pool) else (
        pool_type or "max")
    if kind not in ("max", "avg", "average"):
        kind = "max"
    return _fl.pool2d(input=input, pool_size=pool_size, pool_stride=stride,
                      pool_padding=padding,
                      pool_type="avg" if kind != "max" else "max")


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    from ..fluid import nets as _nets

    return _nets.simple_img_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride, act=_act_name(act))


# --- elementwise / misc layers --------------------------------------------


def addto_layer(input, act=None, **kwargs):
    from ..fluid.layers import tensor as _t

    out = _t.sums(list(input))
    name = _act_name(act)
    if name:
        out = getattr(_fl, name)(out)
    return out


def cos_sim(a, b, **kwargs):
    return _fl.cos_sim(X=a, Y=b)


def scaling_layer(input, weight, **kwargs):
    return _fl.elementwise_mul(input, weight)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, **kwargs):
    return _fl.scale(input, scale=float(slope), bias=float(intercept))


def trans_layer(input, **kwargs):
    return _fl.transpose(input, perm=[1, 0])


def maxid_layer(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.argmax(input, axis=-1)


def dropout_layer(input, dropout_rate, **kwargs):
    return _fl.dropout(input, dropout_prob=dropout_rate)


batch_norm_layer = _fl.batch_norm
conv_layer = img_conv_layer


# --- cost layers (reference classification_cost / regression_cost /
# crf_layer / ctc_layer / rank_cost) ---------------------------------------


def regression_cost(input, label, **kwargs):
    return square_error_cost(input, label)


def mse_cost(input, label, **kwargs):
    return square_error_cost(input, label)


def crf_layer(input, label, param_attr=None, **kwargs):
    return _fl.linear_chain_crf(input=input, label=label,
                                param_attr=param_attr)


def crf_decoding_layer(input, param_attr, label=None, **kwargs):
    return _fl.crf_decoding(input=input, param_attr=param_attr, label=label)


def softmax_layer(input, **kwargs):
    return _fl.softmax(input)
