"""v2 layer DSL mapped onto fluid layers (reference python/paddle/v2/layer.py
+ trainer_config_helpers/layers.py — declarative layers composed by passing
outputs as inputs). Each function appends ops to the implicit default
program, exactly like fluid layers; the v2-specific `data_type` objects
translate to fluid data vars.

Coverage: 114 layer functions vs the reference's 109 names. Intentionally
absent (each a nested-raggedness construct the padded+lengths sequence
model deliberately flattens — SURVEY §5.7):
  - sub_nested_seq_layer: selects inner sequences of a 2-level LoD;
    lod_level-2 data arrives here already flattened to one level.
  - cross_entropy_over_beam: cost over the beam-structured LoD the legacy
    generator emitted; generation here keeps fixed [batch, beam] lanes
    (see beam_search below) where plain cross_entropy applies per lane.
  - layer_support/__cost_input__/__img_norm_layer__: config-parser
    internals, not user layers."""
from __future__ import annotations

from ..fluid import layers as _fl
from ..fluid.param_attr import ParamAttr as _ParamAttr


class _DataType:
    def __init__(self, kind: str, dim: int, seq: bool = False):
        self.kind = kind
        self.dim = dim
        self.seq = seq


class data_type:
    """reference paddle.v2.data_type."""

    @staticmethod
    def dense_vector(dim):
        return _DataType("dense", dim)

    @staticmethod
    def integer_value(dim):
        return _DataType("int", dim)

    @staticmethod
    def integer_value_sequence(dim):
        return _DataType("int", dim, seq=True)

    @staticmethod
    def dense_vector_sequence(dim):
        return _DataType("dense", dim, seq=True)


def data(name, type: _DataType, **kwargs):
    if type.kind == "int":
        shape = [1]
        dtype = "int64"
    else:
        shape = [type.dim]
        dtype = "float32"
    var = _fl.data(name=name, shape=shape, dtype=dtype, **kwargs)
    var._v2_type = type  # embedding_layer sizes its table from this
    return var


def fc_layer(input, size, act=None, **kwargs):
    # sequence inputs ([N, T, D]) project per-timestep, as the legacy
    # config parser did for fc over a sequence layer
    ref = input[0] if isinstance(input, (list, tuple)) else input
    if "num_flatten_dims" not in kwargs and getattr(ref, "shape", None) \
            is not None and len(ref.shape) == 3:
        kwargs["num_flatten_dims"] = 2
    return _fl.fc(input=input, size=size, act=_act_name(act), **kwargs)


def embedding_layer(input, size, vocab_size=None, **kwargs):
    """Table rows come from the input data layer's declared integer dim
    (reference: the v2 config carries the vocab through the data type)."""
    if vocab_size is None:
        t = getattr(input, "_v2_type", None)
        if t is None or t.kind != "int":
            raise ValueError(
                "embedding_layer needs vocab_size= or an input created by "
                "v2.layer.data with integer_value(_sequence)(dim)"
            )
        vocab_size = t.dim
    return _fl.embedding(input, size=[vocab_size, size], **kwargs)


def classification_cost(input, label, weight=None, **kwargs):
    """reference classification_cost; weight is the per-sample cost
    weight the legacy layer took (layers.py classification_cost's
    weight input)."""
    ce = _fl.cross_entropy(input=input, label=label)
    if weight is not None:
        ce = _fl.elementwise_mul(ce, weight)
    return _fl.mean(ce)


def square_error_cost(input, label, weight=None, **kwargs):
    se = _fl.square_error_cost(input=input, label=label)
    if weight is not None:
        se = _fl.elementwise_mul(se, weight)
    return _fl.mean(se)


def cross_entropy_cost(input, label):
    return classification_cost(input, label)


def _concat(input, **kwargs):
    from ..fluid.layers import tensor as _t

    # reference concat_layer accepts projections alongside layers
    ins = [p.realize(p.width) if isinstance(p, _Projection) else p
           for p in input]
    return _t.concat(ins, **kwargs)


concat_layer = _concat


# --- activation / pooling namespaces (reference trainer_config_helpers
# activations.py / poolings.py: layer args take ReluActivation() /
# MaxPooling() instances) ---------------------------------------------------


class _Act:
    def __init__(self, name):
        self.name = name


class activation:
    """reference paddle.v2.activation.*"""

    Relu = staticmethod(lambda: _Act("relu"))
    Sigmoid = staticmethod(lambda: _Act("sigmoid"))
    Tanh = staticmethod(lambda: _Act("tanh"))
    Softmax = staticmethod(lambda: _Act("softmax"))
    Linear = staticmethod(lambda: _Act(None))
    Identity = staticmethod(lambda: _Act(None))


class _Pool:
    def __init__(self, kind):
        self.kind = kind


class pooling:
    """reference paddle.v2.pooling.* (sequence poolings)."""

    Max = staticmethod(lambda: _Pool("max"))
    Avg = staticmethod(lambda: _Pool("average"))
    Sum = staticmethod(lambda: _Pool("sum"))
    SquareRootN = staticmethod(lambda: _Pool("sqrt"))


def _act_name(act):
    return act.name if isinstance(act, _Act) else act


# --- sequence layers (reference trainer_config_helpers/layers.py:
# last_seq, first_seq, pooling_layer, lstmemory, grumemory, simple_lstm,
# simple_gru, expand_layer) -------------------------------------------------


def last_seq(input, **kwargs):
    return _fl.sequence_last_step(input)


def first_seq(input, **kwargs):
    return _fl.sequence_first_step(input)


def pooling_layer(input, pooling_type=None, **kwargs):
    """Sequence pooling (reference pooling_layer) — NOT image pooling
    (that's img_pool_layer)."""
    kind = pooling_type.kind if isinstance(pooling_type, _Pool) else (
        pooling_type or "max")
    return _fl.sequence_pool(input=input, pool_type=kind)


def lstmemory(input, size=None, reverse=False, act=None, **kwargs):
    """reference lstmemory: `size` is the HIDDEN width; the input must
    carry 4*size projected features (pair with fc_layer, as
    trainer_config_helpers documents). Default size = input_width // 4."""
    width = int(input.shape[-1])
    if size is None:
        size = width // 4
    if width != size * 4:
        raise ValueError(
            f"lstmemory(size={size}) needs an input of width {size * 4} "
            f"(4*size projected features), got {width}")
    h, _ = _fl.dynamic_lstm(input=input, size=size * 4, is_reverse=reverse)
    return h


def simple_lstm(input, size, reverse=False, **kwargs):
    """reference networks.simple_lstm: fc projection + lstmemory."""
    proj = _fl.fc(input=input, size=size * 4, num_flatten_dims=2)
    h, _ = _fl.dynamic_lstm(input=proj, size=size * 4, is_reverse=reverse)
    return h


def grumemory(input, size=None, reverse=False, **kwargs):
    """`size` is the hidden width; input carries 3*size projected gates."""
    width = int(input.shape[-1])
    if size is None:
        size = width // 3
    if width != size * 3:
        raise ValueError(
            f"grumemory(size={size}) needs an input of width {size * 3} "
            f"(3*size projected gates), got {width}")
    return _fl.dynamic_gru(input=input, size=size, is_reverse=reverse)


def simple_gru(input, size, reverse=False, **kwargs):
    proj = _fl.fc(input=input, size=size * 3, num_flatten_dims=2)
    return _fl.dynamic_gru(input=proj, size=size, is_reverse=reverse)


def expand_layer(input, expand_as, **kwargs):
    return _fl.sequence_expand(input, expand_as)


# --- image layers (reference img_conv_layer / img_pool_layer /
# simple_img_conv_pool) -----------------------------------------------------


def _as_nchw(input, num_channels=None, height=None, width=None):
    """Flat image data ([N, C*H*W] data layers) to NCHW. The reference
    config parser (trainer/config_parser.py parse_image) infers square
    H = W = sqrt(size / channels) when the data layer carries no
    height/width — the legacy configs rely on that."""
    if input.shape is not None and len(input.shape) >= 4:
        return input
    flat = int(input.shape[-1])
    if not (height and width):
        # data layers carry declared height/width (data_layer(height=,
        # width=)) through _img_hw
        hw = getattr(input, "_img_hw", None)
        if hw:
            height, width = hw
    if height and width:
        h, w = int(height), int(width)
        # reference parse_image: channels = size / (h*w) when undeclared
        c = int(num_channels) if num_channels else max(1, flat // (h * w))
    else:
        c = int(num_channels or 1)
        h = w = int(round((flat // c) ** 0.5))
    if c * h * w != flat:
        raise ValueError(
            f"cannot fold flat image of {flat} features into "
            f"[{c}, {h}, {w}] — pass num_channels/height/width")
    return _fl.reshape(input, shape=[-1, c, h, w])


def img_conv_layer(input, filter_size, num_filters, stride=1, padding=0,
                   act=None, num_channels=None, dilation=1, trans=False,
                   **kwargs):
    input = _as_nchw(input, num_channels)
    conv = _fl.conv2d_transpose if trans else _fl.conv2d
    return conv(input=input, num_filters=num_filters,
                filter_size=filter_size, stride=stride,
                padding=padding, dilation=dilation, act=_act_name(act))


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   num_channels=None, **kwargs):
    kind = pool_type.kind if isinstance(pool_type, _Pool) else (
        pool_type or "max")
    if kind not in ("max", "avg", "average"):
        kind = "max"
    input = _as_nchw(input, num_channels)
    return _fl.pool2d(input=input, pool_size=pool_size, pool_stride=stride,
                      pool_padding=padding,
                      pool_type="avg" if kind != "max" else "max")


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    from ..fluid import nets as _nets

    return _nets.simple_img_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride, act=_act_name(act))


# --- elementwise / misc layers --------------------------------------------


def addto_layer(input, act=None, **kwargs):
    from ..fluid.layers import tensor as _t

    out = _t.sums(list(input))
    name = _act_name(act)
    if name:
        out = getattr(_fl, name)(out)
    return out


def cos_sim(a, b, size=1, **kwargs):
    """reference cos_sim: size>1 treats b as `size` groups of a-width
    vectors and emits one similarity per group ([N, size])."""
    if size and int(size) > 1:
        da = int(a.shape[-1])
        bg = _fl.reshape(b, shape=[-1, int(size), da])
        ag = _fl.reshape(a, shape=[-1, 1, da])
        num = _fl.reduce_sum(_fl.elementwise_mul(bg, ag), dim=-1)
        na = _fl.sqrt(_fl.reduce_sum(_fl.square(ag), dim=-1))
        nb = _fl.sqrt(_fl.reduce_sum(_fl.square(bg), dim=-1))
        return _raw_op("elementwise_div", {"X": [num],
                                           "Y": [_fl.elementwise_mul(na, nb)]})
    return _fl.cos_sim(X=a, Y=b)


def scaling_layer(input, weight, **kwargs):
    return _fl.elementwise_mul(input, weight)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, **kwargs):
    return _fl.scale(input, scale=float(slope), bias=float(intercept))


def trans_layer(input, **kwargs):
    return _fl.transpose(input, perm=[1, 0])


def maxid_layer(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.argmax(input, axis=-1)


def dropout_layer(input, dropout_rate, **kwargs):
    return _fl.dropout(input, dropout_prob=dropout_rate)


batch_norm_layer = _fl.batch_norm
conv_layer = img_conv_layer


# --- cost layers (reference classification_cost / regression_cost /
# crf_layer / ctc_layer / rank_cost) ---------------------------------------


def regression_cost(input, label, **kwargs):
    return square_error_cost(input, label)


def mse_cost(input, label, **kwargs):
    return square_error_cost(input, label)


def crf_layer(input, label, param_attr=None, **kwargs):
    return _fl.linear_chain_crf(input=input, label=label,
                                param_attr=param_attr)


def crf_decoding_layer(input, param_attr, label=None, **kwargs):
    return _fl.crf_decoding(input=input, param_attr=param_attr, label=label)


def softmax_layer(input, **kwargs):
    return _fl.softmax(input)


# --- helper: append a raw op through the fluid LayerHelper ----------------


def _raw_op(op_type, inputs, attrs=None, n_outs=1, dtype=None,
            out_slots=("Out",)):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper(op_type)
    first = next(iter(inputs.values()))
    ref = first[0] if isinstance(first, (list, tuple)) else first
    dtype = dtype or ref.dtype
    outs = [helper.create_variable_for_type_inference(dtype)
            for _ in range(n_outs)]
    helper.append_op(
        type=op_type, inputs=inputs,
        outputs={slot: [o] for slot, o in zip(out_slots, outs)},
        attrs=attrs or {},
    )
    return outs[0] if n_outs == 1 else tuple(outs)


# --- mixed_layer projections / operators (reference
# trainer_config_helpers/layers.py: full_matrix_projection:...,
# identity_projection, table_projection, dotmul_projection,
# context_projection, dotmul_operator). A projection is a deferred spec;
# mixed_layer realizes each against its own `size` and sums them. --------


class _Projection:
    def __init__(self, realize, width=None):
        self.realize = realize  # size -> Variable
        self.width = width  # intrinsic output width, when the projection
        # knows it (lets mixed_layer() omit size, as the reference does)


def full_matrix_projection(input, size=None, param_attr=None, **kwargs):
    def realize(sz):
        sz = sz or size
        if sz is None:
            raise ValueError("full_matrix_projection needs a size (its own "
                             "size= or the enclosing mixed_layer's)")
        # sequence inputs ([N, T, D]) project per-timestep
        flat = 2 if input.shape is not None and len(input.shape) == 3 else 1
        return _fl.fc(input=input, size=sz, act=None, num_flatten_dims=flat,
                      param_attr=param_attr)

    return _Projection(realize, width=size)


def identity_projection(input, offset=None, size=None, **kwargs):
    def realize(sz):
        sz = sz or size
        if offset is not None:
            if sz is None:
                raise ValueError("identity_projection(offset=...) needs a "
                                 "size to know the slice width")
            return _raw_op("slice", {"Input": [input]},
                           {"axes": [input.ndim - 1 if hasattr(input, "ndim")
                                     else len(input.shape) - 1],
                            "starts": [offset], "ends": [offset + sz]})
        return input

    width = size if offset is not None else int(input.shape[-1])
    return _Projection(realize, width=width)


def table_projection(input, size=None, **kwargs):
    t = getattr(input, "_v2_type", None)
    vocab = t.dim if t is not None else None

    def realize(sz):
        sz = sz or size
        if vocab is None:
            # the reference parses (never executes) table projections over
            # non-id layers (tests/configs/projections.py feeds a mixed
            # output); the executable analogue of "the id this activation
            # denotes" is its argmax over the feature width
            ids = _fl.reshape(_fl.argmax(input, axis=-1), shape=[-1, 1])
            return _fl.embedding(ids, size=[int(input.shape[-1]), sz])
        return _fl.embedding(input, size=[vocab, sz])

    return _Projection(realize, width=size)


def dotmul_projection(input, **kwargs):
    def realize(sz):
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("dotmul_projection")
        w = helper.create_parameter(
            helper.param_attr, shape=[int(input.shape[-1])],
            dtype=input.dtype)
        return _fl.elementwise_mul(input, w)

    return _Projection(realize, width=int(input.shape[-1]))


def context_projection(input, context_len=3, context_start=None, **kwargs):
    """Concat each timestep with its neighbours (reference
    context_projection -> math/context_project)."""
    def realize(sz):
        from ..fluid.layers.sequence import seq_lengths_of

        x = input
        flat = x.shape is not None and len(x.shape) == 2
        if flat:
            # non-sequence input (parse-only in the reference): a context
            # window over a length-1 sequence — neighbours are padding
            x = _fl.reshape(x, shape=[-1, 1, int(x.shape[-1])])
        inputs = {"X": [x]}
        lens = seq_lengths_of(input)
        if lens is not None:
            inputs["Lengths"] = [lens]
        attrs = {"context_length": context_len}
        if context_start is not None:
            attrs["context_start"] = context_start
        out = _raw_op("context_project", inputs, attrs)
        if flat:
            out = _fl.reshape(
                out, shape=[-1, int(input.shape[-1]) * context_len])
        return out

    return _Projection(realize, width=int(input.shape[-1]) * context_len)


def dotmul_operator(a, b, scale=1.0, **kwargs):
    return _Projection(lambda sz: _fl.scale(_fl.elementwise_mul(a, b),
                                            scale=float(scale)),
                       width=int(a.shape[-1]))


class _MixedBuilder:
    """Deferred mixed_layer (reference `with mixed_layer(size=N) as m:
    m += projection` — trainer_config_helpers/layers.py mixed_layer's
    context-manager form). Projections accumulate via `+=`; the summed
    layer realizes when the `with` block exits. Afterwards the builder
    proxies the realized Variable (shape/dtype/name), so it can feed
    later layers."""

    def __init__(self, size, act, bias_attr, kwargs):
        self._spec = (size, act, bias_attr, kwargs)
        self._projs = []
        self._var = None

    def __iadd__(self, proj):
        if self._var is not None:
            raise RuntimeError("mixed_layer already realized; += is only "
                               "valid inside the `with` block")
        self._projs.append(proj)
        return self

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if et is None:
            self.to_variable()
        return False

    def to_variable(self):
        if self._var is None:
            size, act, bias_attr, kw = self._spec
            if not self._projs:
                raise ValueError("mixed_layer realized with no projections")
            self._var = mixed_layer(size=size, input=self._projs, act=act,
                                    bias_attr=bias_attr, **kw)
        return self._var

    def __getattr__(self, item):
        return getattr(self.to_variable(), item)


def mixed_layer(*args, size=None, input=None, act=None, bias_attr=None,
                **kwargs):
    """reference mixed_layer: sum of realized projections/operators, then
    activation. Plain Variables act as full-matrix projections. Accepted
    call forms: mixed_layer(size=N, input=[...]) (reference kwargs),
    mixed_layer(inputs, N), mixed_layer(inputs, size=N) (legacy positional
    input), and the no-input context-manager form (`with mixed_layer(...)
    as m: m += proj`), where size may be omitted if every projection
    declares its own width."""
    for a in args:  # positional args: ints are size, everything else input
        if isinstance(a, int):
            size = a
        else:
            input = a
    if input is None:
        return _MixedBuilder(size, act, bias_attr, kwargs)
    ins = input if isinstance(input, (list, tuple)) else [input]
    widths = [p.width for p in ins
              if isinstance(p, _Projection) and p.width is not None]
    if size is None:
        if not widths:
            raise TypeError("mixed_layer needs an integer size (none of "
                            "its projections declares an output width)")
        size = widths[0]
    bad = [w for w in widths if w != size]
    if bad:
        # the reference config parser rejects mismatched projection sizes;
        # silently overriding would build a different architecture
        raise ValueError(
            f"mixed_layer(size={size}) has projections declaring widths "
            f"{sorted(set(widths))} — every projection must produce the "
            "layer's width")
    realized = []
    for p in ins:
        if isinstance(p, _MixedBuilder):
            p = p.to_variable()
        if isinstance(p, _Projection):
            realized.append(p.realize(size))
        else:
            realized.append(_fl.fc(input=p, size=size, act=None)
                            if size else p)
    out = realized[0]
    for r in realized[1:]:
        out = _fl.elementwise_add(out, r)
    name = _act_name(act)
    if name:
        out = getattr(_fl, name)(out)
    return out


# --- elementwise / arithmetic layers (reference layers.py interpolation,
# power, sum_to_one_norm, row_l2_norm, dot_prod, out_prod, linear_comb,
# l2_distance, clip, scale_shift, slope_intercept) ------------------------


def interpolation_layer(input, weight, **kwargs):
    """out = w*x + (1-w)*y with input=[x, y], per-row weight in [0,1]."""
    x, y = input
    wx = _fl.elementwise_mul(x, weight)
    one_minus = _fl.scale(weight, scale=-1.0, bias=1.0)
    wy = _fl.elementwise_mul(y, one_minus)
    return _fl.elementwise_add(wx, wy)


def power_layer(input, weight, **kwargs):
    return _raw_op("elementwise_pow", {"X": [input], "Y": [weight]})


def sum_to_one_norm_layer(input, **kwargs):
    s = _fl.reduce_sum(input, dim=-1, keep_dim=True)
    return _raw_op("elementwise_div", {"X": [input], "Y": [s]})


def row_l2_norm_layer(input, **kwargs):
    return _fl.l2_normalize(input, axis=-1)


def dot_prod_layer(a=None, b=None, input1=None, input2=None, **kwargs):
    a = a if a is not None else input1  # reference spells them input1/2
    b = b if b is not None else input2
    return _fl.reduce_sum(_fl.elementwise_mul(a, b), dim=-1, keep_dim=True)


def out_prod_layer(a, b, **kwargs):
    """Per-row outer product flattened to [N, da*db] (reference
    out_prod_layer)."""
    da, db = int(a.shape[-1]), int(b.shape[-1])
    am = _fl.reshape(a, shape=[-1, da, 1])
    bm = _fl.reshape(b, shape=[-1, 1, db])
    return _fl.reshape(_fl.matmul(am, bm), shape=[-1, da * db])


def linear_comb_layer(weights, vectors, size=None, **kwargs):
    """Rowwise weighted sum of `size`-dim sub-vectors (reference
    linear_comb_layer): vectors [N, m*size] grouped by weights [N, m];
    size defaults to vectors_width / weights_width (the reference's
    inferred form)."""
    m = int(weights.shape[-1])
    if size is None:
        size = int(vectors.shape[-1]) // m
    v = _fl.reshape(vectors, shape=[-1, m, size])
    w = _fl.reshape(weights, shape=[-1, m, 1])
    return _fl.reshape(_fl.reduce_sum(_fl.elementwise_mul(v, w), dim=1),
                       shape=[-1, size])


def l2_distance_layer(x, y, **kwargs):
    return _raw_op("squared_l2_distance", {"X": [x], "Y": [y]},
                   n_outs=2, out_slots=("Out", "sub_result"))[0]


def clip_layer(input, min, max, **kwargs):
    return _fl.clip(input, min=float(min), max=float(max))


def scale_shift_layer(input, **kwargs):
    """y = w*x + b with SCALAR learnable w, b (reference
    scale_shift_layer)."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("scale_shift")
    w = helper.create_parameter(helper.param_attr, shape=[1],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.param_attr, shape=[1],
                                dtype=input.dtype, is_bias=True)
    return _fl.elementwise_add(_fl.elementwise_mul(input, w), b)


def sum_cost(input, **kwargs):
    return _fl.reduce_sum(input)


# --- shape / image manipulation layers (reference repeat_layer, pad,
# crop, rotate, resize, maxout, spp, img_cmrnorm, roi_pool, bilinear) ------


def repeat_layer(input, num_repeats, act=None, **kwargs):
    times = [1] * (len(input.shape) - 1) + [int(num_repeats)]
    out = _raw_op("expand", {"X": [input]}, {"expand_times": times})
    name = _act_name(act)
    return getattr(_fl, name)(out) if name else out


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, **kwargs):
    """NCHW padding (reference pad_layer pads channel/height/width)."""
    paddings = [0, 0]
    for p in (pad_c, pad_h, pad_w):
        p = p or [0, 0]
        paddings += list(p)
    return _fl.pad(input, paddings=paddings)


def crop_layer(input, shape=None, offsets=None, **kwargs):
    attrs = {}
    if shape is not None:
        attrs["shape"] = list(shape)
    if offsets is not None:
        attrs["offsets"] = list(offsets)
    return _raw_op("crop", {"X": [input]}, attrs)


def rotate_layer(input, height, width, **kwargs):
    """90-degree CCW rotation of each feature map (reference rotate_layer:
    transpose H/W then reverse the new height axis)."""
    c = int(input.shape[1]) if len(input.shape) > 3 else 1
    x = _fl.reshape(input, shape=[-1, c, height, width])
    t = _fl.transpose(x, perm=[0, 1, 3, 2])
    return _raw_op("reverse", {"X": [t]}, {"axis": [2]})


def resize_layer(input, size, **kwargs):
    return _fl.reshape(input, shape=[-1, int(size)])


def maxout_layer(input, groups, **kwargs):
    return _raw_op("maxout", {"X": [input]}, {"groups": int(groups)})


def spp_layer(input, pyramid_height, pool_type=None, num_channels=None,
              **kwargs):
    kind = pool_type.kind if isinstance(pool_type, _Pool) else (
        pool_type or "max")
    return _raw_op("spp", {"X": [_as_nchw(input, num_channels)]},
                   {"pyramid_height": int(pyramid_height),
                    "pooling_type": "avg" if kind != "max" else "max"})


def img_cmrnorm_layer(input, size=5, scale=0.0128, power=0.75,
                      num_channels=None, **kwargs):
    """Local response norm across channels (reference img_cmrnorm_layer ->
    lrn op; alpha = scale/size per the config_parser translation)."""
    return _fl.lrn(_as_nchw(input, num_channels), n=int(size),
                   alpha=float(scale) / int(size), beta=float(power))


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale=1.0, **kwargs):
    return _raw_op("roi_pool", {"X": [input], "ROIs": [rois]},
                   {"pooled_height": int(pooled_height),
                    "pooled_width": int(pooled_width),
                    "spatial_scale": float(spatial_scale)})


def print_layer(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.Print(input) if hasattr(_t, "Print") else input


# --- sequence layers (reference seq_concat, seq_reshape, seq_slice,
# sub_seq via slice, context window via row_conv) --------------------------


def seq_concat_layer(a, b, **kwargs):
    return _fl.sequence_concat([a, b])


def seq_reshape_layer(input, reshape_size, **kwargs):
    return _fl.sequence_reshape(input, new_dim=int(reshape_size))


def seq_slice_layer(input, starts, ends, **kwargs):
    """reference seq_slice_layer: keep [start_i, end_i) windows. starts /
    ends may carry SEVERAL columns (k windows per sequence — the
    reference emitted a nested sequence); the masked-sequence model keeps
    [N, T, D] with the union of the windows valid. None starts = from 0,
    None ends = to each sequence's length."""
    from ..fluid.layers.sequence import seq_lengths_of

    if starts is None and ends is None:
        return input
    if starts is None:
        starts = _fl.scale(ends, scale=0.0)
    if ends is None:
        lens = seq_lengths_of(input)
        big = _fl.fill_constant(shape=[1], dtype=starts.dtype,
                                value=float(input.shape[1] or 10 ** 6)) \
            if lens is None else _fl.reshape(_fl.cast(lens, starts.dtype),
                                             shape=[-1, 1])
        ends = _fl.elementwise_add(_fl.scale(starts, scale=0.0), big)
    length = _fl.elementwise_sub(ends, starts)
    return _raw_op("sequence_slice",
                   {"X": [input], "Offset": [starts], "Length": [length]})


def row_conv_layer(input, context_len, **kwargs):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("row_conv")
    w = helper.create_parameter(
        helper.param_attr, shape=[int(context_len), int(input.shape[-1])],
        dtype=input.dtype)
    return _raw_op("row_conv", {"X": [input], "Filter": [w]})


# --- recurrent step layers (reference gru_step_layer, lstm_step_layer) ----


def gru_step_layer(input, output_mem, size=None, **kwargs):
    size = size or int(output_mem.shape[-1])
    from ..fluid.layers import sequence as _seq

    h, _, _ = _seq.gru_unit(input=input, hidden=output_mem, size=size * 3)
    return h


def lstm_step_layer(input, state, size=None, **kwargs):
    """One LSTM step (reference lstm_step_layer): input carries 4*size
    gates; state is the previous cell. Returns (hidden, new_cell)."""
    size = size or int(state.shape[-1])
    c, h = _raw_op("lstm_unit", {"X": [input], "C_prev": [state]},
                   n_outs=2, out_slots=("C", "H"))
    return h, c


# --- cost layers ----------------------------------------------------------


def rank_cost(left, right, label, **kwargs):
    return _fl.mean(_raw_op("rank_loss",
                            {"Left": [left], "Right": [right],
                             "Label": [label]}))


def huber_regression_cost(input, label, delta=1.0, **kwargs):
    return _fl.mean(_raw_op("huber_loss", {"X": [input], "Y": [label]},
                            {"delta": float(delta)}, n_outs=2,
                            out_slots=("Out", "Residual"))[0])


def huber_classification_cost(input, label, **kwargs):
    """reference huber_classification_cost (modified huber on +-1
    labels)."""
    return _fl.mean(_raw_op("modified_huber_loss",
                            {"X": [input], "Y": [label]}, n_outs=2,
                            out_slots=("Out", "IntermediateVal"))[0])


def multi_binary_label_cross_entropy(input, label, **kwargs):
    return _fl.mean(_fl.sigmoid_cross_entropy_with_logits(x=input,
                                                          label=label))


def smooth_l1_cost(input, label, **kwargs):
    return _fl.mean(_fl.smooth_l1(x=input, y=label))


def nce_layer(input, label, num_classes=None, num_neg_samples=10, **kwargs):
    from ..fluid.layer_helper import LayerHelper

    if num_classes is None:
        # the reference derived it from the label data layer's size
        t = getattr(label, "_v2_type", None)
        if t is None:
            raise ValueError("nce_layer needs num_classes= or a label "
                             "created by v2.layer.data")
        num_classes = t.dim
    helper = LayerHelper("nce_layer")
    dim = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[int(num_classes), dim],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.param_attr,
                                shape=[int(num_classes)],
                                dtype=input.dtype, is_bias=True)
    return _fl.mean(_raw_op(
        "nce", {"Input": [input], "Label": [label], "Weight": [w],
                "Bias": [b]},
        {"num_total_classes": int(num_classes),
         "num_neg_samples": int(num_neg_samples)},
        n_outs=3, out_slots=("Cost", "SampleLogits", "SampleLabels"))[0])


def ctc_layer(input, label, blank=0, **kwargs):
    return _fl.mean(_raw_op("warpctc", {"Logits": [input],
                                        "Label": [label]},
                            {"blank": int(blank)},
                            out_slots=("Loss",)))


warp_ctc_layer = ctc_layer


# --- recurrent_group (reference trainer_config_helpers recurrent_group +
# memory + StaticInput — the legacy DSL's custom-RNN API, backed here by
# fluid's DynamicRNN masked-scan lowering) ---------------------------------


class StaticInput:
    """Non-sequence input visible at every step (reference
    paddle.layer.StaticInput)."""

    def __init__(self, input, **kwargs):
        self.input = input


_current_group = None


def memory(name=None, size=None, boot_layer=None, **kwargs):
    """Declare a recurrent state inside a recurrent_group step (reference
    paddle.layer.memory): returns the PREVIOUS step's value. The state the
    step returns (single-memory form) or the returned output whose
    position matches the memory's declaration order feeds the next step."""
    if _current_group is None:
        raise RuntimeError("memory() is only valid inside a "
                           "recurrent_group step function")
    return _current_group._declare_memory(name, size, boot_layer)


class _GroupCtx:
    def __init__(self, drnn):
        self.drnn = drnn
        self.declared = []  # pre-mem vars, in declaration order
        self.explicit = {}  # id(pre) -> update var, via memory.set_input

    def _declare_memory(self, name, size, boot_layer):
        if boot_layer is not None:
            pre = self.drnn.memory(init=boot_layer)
        elif size is not None:
            pre = self.drnn.memory(shape=[int(size)], value=0.0)
        else:
            raise ValueError(
                "memory() requires size= or boot_layer= (the reference's "
                "link-by-name form resolves sizes from the parsed config; "
                "here the state width must be explicit)")
        self.declared.append(pre)
        # reference memory.set_input (trainer_config_helpers/layers.py
        # MemoryV2.set_input): explicitly name the layer that feeds the
        # next step, overriding positional output matching
        pre.set_input = lambda v: self.explicit.__setitem__(id(pre), v)
        return pre


def recurrent_group(step, input, reverse=False, **kwargs):
    """reference recurrent_group: run `step` once per timestep over the
    sequence input(s); memories declared via layer.memory carry state.
    The step's outputs update the memories in declaration order (the
    single-memory/single-output form is the reference's dominant usage);
    extra outputs beyond the declared memories are emitted only; a
    memory.set_input(layer) overrides the positional match.
    reverse=True runs the steps last-to-first: each sequence input's valid
    prefix is flipped before the scan and the emitted sequence flipped
    back, so output[t] is the state after consuming t..end — the
    reference's reversed-group semantics without a backward scan."""
    global _current_group

    from ..fluid.layers.control_flow import DynamicRNN
    from ..fluid.layers.sequence import sequence_reverse

    ins = input if isinstance(input, (list, tuple)) else [input]
    if reverse:
        ins = [x if isinstance(x, StaticInput) else sequence_reverse(x)
               for x in ins]
    drnn = DynamicRNN()
    prev = _current_group
    mismatch = None
    with drnn.block():
        step_args = []
        for x in ins:
            if isinstance(x, StaticInput):
                step_args.append(drnn.static_input(x.input))
            else:
                step_args.append(drnn.step_input(x))
        _current_group = _GroupCtx(drnn)
        step_exc = None
        try:
            outs = step(*step_args)
        except Exception as e:
            # a raw raise here would be shadowed by DynamicRNN._complete()
            # (block()'s finally asserts every memory updated) — self-link
            # the declared state so the USER's error survives the exit
            step_exc = e
            outs = []
        finally:
            ctx, _current_group = _current_group, prev
        if step_exc is not None:
            for mem in ctx.declared:
                drnn.update_memory(mem, mem)
            drnn.output(*(ctx.declared or step_args[:1]))
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        positional = [m for m in ctx.declared if id(m) not in ctx.explicit]
        if positional and len(outs) < len(positional):
            # raising here would be shadowed by DynamicRNN._complete()'s
            # own invariant (block()'s finally) — still update what we can
            # so the clearer error below is the one the user sees
            mismatch = (len(outs), len(positional))
        if step_exc is None:
            for mem in ctx.declared:
                if id(mem) in ctx.explicit:
                    drnn.update_memory(mem, ctx.explicit[id(mem)])
            for mem, out in zip(positional, outs):
                drnn.update_memory(mem, out)
            for mem in positional[len(outs):]:
                drnn.update_memory(mem, mem)  # satisfy the block invariant;
                # the ValueError below is the error the user actually sees
            drnn.output(*outs)
    if step_exc is not None:
        raise step_exc
    if mismatch is not None:
        raise ValueError(
            f"step returned {mismatch[0]} outputs but declared "
            f"{mismatch[1]} memories — each memory updates from the "
            "same-position output")
    result = drnn()  # DynamicRNN() unwraps a single output itself
    if reverse:
        result = ([sequence_reverse(r) for r in result]
                  if isinstance(result, (list, tuple))
                  else sequence_reverse(result))
    return result


def recurrent_layer(input, act=None, reverse=False, **kwargs):
    """Simple Elman recurrence (reference recurrent_layer):
    h_t = act(x_t + W h_{t-1}) — the input carries the ALREADY-projected
    x, so only the recurrent weight W is learned here (pair with fc_layer
    for the input projection, as the legacy configs do)."""
    size = int(input.shape[-1])
    # default act is tanh (reference recurrent_layer); an EXPLICIT
    # Linear()/Identity() activation means no nonlinearity, not tanh
    act_name = "tanh" if act is None else _act_name(act)

    def step(x_t):
        h_prev = memory(size=size)
        rec = _fl.fc(input=h_prev, size=size, act=None)
        h = _fl.elementwise_add(x_t, rec)
        if act_name:
            h = getattr(_fl, act_name)(h)
        return h

    return recurrent_group(step=step, input=input, reverse=reverse)


# --- round-4 DSL breadth: the long tail of trainer_config_helpers/layers.py
# mapped onto fluid ops (reference layers.py — 109 layer types; each function
# below names its reference counterpart) ------------------------------------


def data_layer(name, size, **kwargs):
    """reference data_layer(name, size): raw config-helper spelling —
    v2's data(name, type) wraps it; size is the flat feature dim."""
    return data(name, data_type.dense_vector(size), **kwargs)


def cross_entropy(input, label, **kwargs):
    """reference cross_entropy (config-helper spelling of the cost)."""
    return classification_cost(input, label)


def batch_norm_layer(input, act=None, bias_attr=None, param_attr=None,
                     use_global_stats=None, moving_average_fraction=0.9,
                     num_channels=None, img3D=False, **kwargs):
    """reference batch_norm_layer -> fluid batch_norm (img3D folds flat
    volumetric data to NCDHW first; channel axis is 1 either way)."""
    if img3D:
        input = _as_ncdhw(input, num_channels)
    return _fl.batch_norm(
        input, act=_act_name(act),
        is_test=bool(use_global_stats) if use_global_stats is not None
        else False,
        momentum=moving_average_fraction,
        param_attr=param_attr, bias_attr=bias_attr)


def tensor_layer(a, b, size, act=None, **kwargs):
    """reference tensor_layer: out_k = a^T W_k b (a bilinear form per
    output) -> fluid bilinear_tensor_product op."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("tensor_layer")
    w = helper.create_parameter(
        helper.param_attr,
        shape=[size, int(a.shape[-1]), int(b.shape[-1])], dtype=a.dtype)
    out = _raw_op("bilinear_tensor_product",
                  {"X": [a], "Y": [b], "Weight": [w]})
    name = _act_name(act)
    return getattr(_fl, name)(out) if name else out


def gated_unit_layer(input, size, act=None, gate_act=None,
                     gate_param_attr=None, gate_bias_attr=None,
                     inproj_param_attr=None, inproj_bias_attr=None,
                     **kwargs):
    """reference gated_unit_layer: act(fc(x)) * gate_act(fc(x))."""
    proj = _fl.fc(input=input, size=size, act=_act_name(act),
                  param_attr=inproj_param_attr, bias_attr=inproj_bias_attr)
    gate = _fl.fc(input=input, size=size,
                  act=_act_name(gate_act) or "sigmoid",
                  param_attr=gate_param_attr, bias_attr=gate_bias_attr)
    return _fl.elementwise_mul(proj, gate)


def prelu_layer(input, partial_sum=1, param_attr=None, num_channels=None,
                channel_shared=None, **kwargs):
    """reference prelu_layer: partial_sum counts elements SHARING one
    alpha — 1 = element-wise (the reference default), the whole feature =
    one shared alpha; channel_shared=False is per-channel alpha over NCHW.
    Intermediate partial_sum groupings (a specific pixel tiling) map to
    the shared form."""
    if channel_shared is False or (num_channels and partial_sum == 1
                                   and channel_shared is None):
        return _fl.prelu(_as_nchw(input, num_channels), mode="channel",
                         param_attr=param_attr)
    mode = "element" if partial_sum == 1 else "all"
    return _fl.prelu(input, mode=mode, param_attr=param_attr)


def multiplex_layer(input, **kwargs):
    """reference multiplex_layer: input[0] is the per-row selector index,
    the rest are candidate tensors."""
    index, candidates = input[0], list(input[1:])
    return _fl.multiplex(candidates, index)


def kmax_seq_score_layer(input, beam_size=1, **kwargs):
    """reference kmax_seq_score_layer: top-k scores over the sequence
    axis. Padded positions are masked to -inf first when the input
    carries lengths — beam scores are log-probs (negative), so unmasked
    zero padding would otherwise win the top-k."""
    from ..fluid.layers.sequence import seq_lengths_of

    scores = input
    lens = seq_lengths_of(input)
    if scores.shape is not None and len(scores.shape) == 3 \
            and scores.shape[-1] == 1:
        scores = _fl.reshape(scores, shape=[0, -1])  # [N, T, 1] -> [N, T]
    if lens is not None:
        from ..fluid.layers.sequence import sequence_mask as _seq_mask

        mask = _seq_mask(lens, maxlen_ref=scores, dtype="float32")  # [N,T]
        # masked = scores*mask + (mask-1)*1e30: valid scores unchanged,
        # padding pushed to -1e30 so it can never enter the top-k
        neg = _fl.scale(_fl.elementwise_sub(
            mask, _fl.fill_constant(shape=[1], dtype=scores.dtype,
                                    value=1.0)), scale=1e30)
        scores = _fl.elementwise_add(_fl.elementwise_mul(scores, mask), neg)
    vals, _ = _fl.topk(scores, k=beam_size)
    return vals


def sub_seq_layer(input, offsets, sizes, **kwargs):
    """reference sub_seq_layer -> sequence_slice op (per-sequence
    offset/size; padded form masks outside the slice)."""
    return _raw_op("sequence_slice",
                   {"X": [input], "Offset": [offsets], "Length": [sizes]})


def switch_order_layer(input, reshape_axis=None, **kwargs):
    """reference switch_order_layer: NCHW <-> NHWC (reshape_axis names the
    split point; the legacy configs only use the [3] <-> channel-last
    form)."""
    return _fl.transpose(input, perm=[0, 2, 3, 1])


def upsample_layer(input, scale=2, upsample_size=None, **kwargs):
    """reference upsample_layer (nearest): integer `scale` repeats
    rows/cols via expand; an explicit `upsample_size` (or (w, h) pair)
    resizes to exactly that via the nearest_interp op."""
    n, c, h, w = [int(s) if s != -1 else -1 for s in input.shape]
    if upsample_size is not None:
        if isinstance(upsample_size, (list, tuple)):
            ow, oh = int(upsample_size[0]), int(upsample_size[1])
        else:
            ow = oh = int(upsample_size)
        return _raw_op("nearest_interp", {"X": [input]},
                       {"out_h": oh, "out_w": ow})
    x = _fl.reshape(input, shape=[-1, c, h, 1, w, 1])
    x = _fl.expand(x, expand_times=[1, 1, 1, scale, 1, scale])
    return _fl.reshape(x, shape=[-1, c, h * scale, w * scale])


def warp_ctc_layer(input, label, blank=0, norm_by_times=False, **kwargs):
    """reference warp_ctc_layer -> fluid warpctc."""
    return _fl.warpctc(input, label, blank=blank,
                       norm_by_times=norm_by_times)


def factorization_machine(input, factor_size, **kwargs):
    """reference factorization_machine: second-order interactions
    0.5 * sum_f [(sum_i v_if x_i)^2 - sum_i (v_if x_i)^2]."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("factorization_machine")
    d = int(input.shape[-1])
    v = helper.create_parameter(helper.param_attr, shape=[d, factor_size],
                                dtype=input.dtype)
    xv = _fl.matmul(input, v)                      # [N, F]
    sq_of_sum = _fl.elementwise_mul(xv, xv)
    x2 = _fl.elementwise_mul(input, input)
    v2 = _fl.elementwise_mul(v, v)
    sum_of_sq = _fl.matmul(x2, v2)                 # [N, F]
    diff = _fl.elementwise_sub(sq_of_sum, sum_of_sq)
    return _fl.scale(_fl.reduce_sum(diff, dim=-1, keep_dim=True), scale=0.5)


def _as_ncdhw(input, num_channels=None):
    """Flat volumetric data ([N, C*D*H*W] data layers with declared
    depth/height/width) to NCDHW (reference parse_image3d)."""
    if input.shape is not None and len(input.shape) >= 5:
        return input
    c = int(num_channels or 1)
    dims = getattr(input, "_img_dhw", None)
    if dims is None:
        raise ValueError("3d image layers over flat data need a data layer "
                         "declared with depth=/height=/width=")
    d, h, w = dims
    return _fl.reshape(input, shape=[-1, c, int(d), int(h), int(w)])


def img_conv3d_layer(input, filter_size, num_filters, stride=1, padding=0,
                     act=None, num_channels=None, groups=1, trans=False,
                     **kwargs):
    """reference img_conv3d_layer -> conv3d / conv3d_transpose op (NCDHW,
    OIDHW filter; trans / layer_type="deconv3d" is the transposed form)."""
    from ..fluid.layer_helper import LayerHelper

    trans = trans or kwargs.get("layer_type") == "deconv3d"
    input = _as_ncdhw(input, num_channels)
    helper = LayerHelper("img_conv3d")

    def _triple(v):
        return [int(x) for x in v] if isinstance(v, (list, tuple)) \
            else [int(v)] * 3

    k = _triple(filter_size)
    c = int(input.shape[1])
    op, shape = ("conv3d_transpose", [c, num_filters] + k) if trans else \
        ("conv3d", [num_filters, c] + k)
    w = helper.create_parameter(helper.param_attr, shape=shape,
                                dtype=input.dtype)
    out = _raw_op(op, {"Input": [input], "Filter": [w]},
                  {"strides": _triple(stride), "paddings": _triple(padding),
                   "groups": int(groups or 1)},
                  out_slots=("Output",))
    name = _act_name(act)
    return getattr(_fl, name)(out) if name else out


def img_pool3d_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                     num_channels=None, **kwargs):
    """reference img_pool3d_layer -> pool3d op."""
    kind = pool_type.kind if isinstance(pool_type, _Pool) else (
        pool_type or "max")
    if kind in ("average", "sqrt", "sum"):
        kind = "avg"
    input = _as_ncdhw(input, num_channels)
    k = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    stride = [stride] * 3 if isinstance(stride, int) else list(stride)
    padding = [padding] * 3 if isinstance(padding, int) else list(padding)
    return _raw_op("pool3d", {"X": [input]},
                   {"pooling_type": kind, "ksize": k,
                    "strides": stride, "paddings": padding})


def cross_channel_norm_layer(input, param_attr=None, **kwargs):
    """reference cross_channel_norm_layer: per-pixel L2 norm across
    channels with a learned per-channel scale (the SSD conv4_3 norm)."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("cross_channel_norm", param_attr=param_attr)
    c = int(input.shape[1])
    normed = _fl.l2_normalize(input, axis=1)
    s = helper.create_parameter(helper.param_attr, shape=[1, c, 1, 1],
                                dtype=input.dtype)
    return _fl.elementwise_mul(normed, s)


def priorbox_layer(input, image, min_size, max_size=None, aspect_ratio=None,
                   variance=(0.1, 0.1, 0.2, 0.2), **kwargs):
    """reference priorbox_layer -> fluid prior_box (SSD anchors)."""
    from ..fluid.layers import detection as _det

    boxes, variances = _det.prior_box(
        input, image, min_sizes=list(min_size),
        max_sizes=list(max_size) if max_size else None,
        aspect_ratios=list(aspect_ratio) if aspect_ratio else [1.0],
        variance=list(variance))
    # legacy layout: [P, 8] = boxes || variances per prior — EXACTLY what
    # detection_output_layer splits back apart
    from ..fluid.layers import tensor as _t

    b = _fl.reshape(boxes, shape=[-1, 4])
    v = _fl.reshape(variances, shape=[-1, 4])
    return _t.concat([b, v], axis=1)


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                           confidence_threshold=0.01, background_id=0,
                           **kwargs):
    """reference detection_output_layer -> fluid detection_output (decode +
    per-class NMS). priorbox here is the [P, 8] concat the legacy layer
    produced (boxes||variances); fluid takes them separately."""
    from ..fluid.layers import detection as _det

    p = int(priorbox.shape[-1]) // 2 if priorbox.shape is not None else None
    boxes = _raw_op("slice", {"Input": [priorbox]},
                    {"axes": [len(priorbox.shape) - 1], "starts": [0],
                     "ends": [p]}) if p else priorbox
    var = _raw_op("slice", {"Input": [priorbox]},
                  {"axes": [len(priorbox.shape) - 1], "starts": [p],
                   "ends": [2 * p]}) if p else priorbox
    return _det.detection_output(
        input_loc, input_conf, boxes, var,
        nms_threshold=nms_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, score_threshold=confidence_threshold,
        background_label=background_id)


def selective_fc_layer(input, size, select=None, act=None, **kwargs):
    """reference selective_fc_layer: a full fc whose output is masked to
    the selected columns (the reference computes only selected columns;
    on the MXU the dense matmul + mask IS the fast form)."""
    out = _fl.fc(input=input, size=size, act=_act_name(act))
    if select is not None:
        out = _fl.elementwise_mul(out, select)
    return out


def eos_layer(input, eos_id, **kwargs):
    """reference eos_layer: 1.0 where the id equals eos_id."""
    eos = _fl.fill_constant(shape=[1], dtype=input.dtype, value=eos_id)
    return _fl.cast(_fl.equal(input, eos), "float32")


def get_output_layer(input, arg_name=None, **kwargs):
    """reference get_output_layer: project out a named auxiliary output of
    a multi-output layer. Fluid layers return their outputs directly, so
    this is the identity on whichever output the caller picked."""
    return input


def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha=0.1,
                                **kwargs):
    """reference cross_entropy_with_selfnorm: CE + alpha * (log Z)^2
    self-normalization on the softmax partition function."""
    ce = _fl.cross_entropy(input=input, label=label)
    z = _fl.reduce_sum(input, dim=-1, keep_dim=True)
    logz = _raw_op("log", {"X": [z]})
    penalty = _fl.scale(_fl.elementwise_mul(logz, logz),
                        scale=float(softmax_selfnorm_alpha))
    return _fl.mean(_fl.elementwise_add(ce, penalty))


def scaling_projection(input, **kwargs):
    """reference scaling_projection: one learned scalar times the input."""
    def realize(sz):
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("scaling_projection")
        s = helper.create_parameter(helper.param_attr, shape=[1],
                                    dtype=input.dtype)
        return _fl.elementwise_mul(input, s)

    return _Projection(realize, width=int(input.shape[-1]))


def trans_full_matrix_projection(input, size=None, **kwargs):
    """reference trans_full_matrix_projection: project through W^T (shares
    no weight here — the legacy sharing came from param_attr naming, which
    callers can still pass)."""
    def realize(sz):
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("trans_full_matrix_projection")
        sz = sz or size
        w = helper.create_parameter(helper.param_attr,
                                    shape=[sz, int(input.shape[-1])],
                                    dtype=input.dtype)
        return _fl.matmul(input, w, transpose_y=True)

    return _Projection(realize, width=size)


def slice_projection(input, slices, **kwargs):
    """reference slice_projection: concat of [start, end) column slices."""
    def realize(sz):
        parts = []
        axis = len(input.shape) - 1
        for start, end in slices:
            parts.append(_raw_op("slice", {"Input": [input]},
                                 {"axes": [axis], "starts": [start],
                                  "ends": [end]}))
        if len(parts) == 1:
            return parts[0]
        from ..fluid.layers import tensor as _t

        return _t.concat(parts, axis=axis)

    return _Projection(realize,
                       width=sum(end - start for start, end in slices))


def conv_projection(input, filter_size, num_filters, stride=1, padding=0,
                    num_channels=None, trans=False, **kwargs):
    """reference conv_projection (a conv2d usable inside mixed_layer);
    trans=True is the deconv form (reference conv_projection's trans
    flag)."""
    img = _as_nchw(input, num_channels)
    k = int(filter_size)
    h, w = int(img.shape[2]), int(img.shape[3])
    if trans:
        oh = (h - 1) * stride - 2 * padding + k
        ow = (w - 1) * stride - 2 * padding + k
    else:
        oh = (h + 2 * padding - k) // stride + 1
        ow = (w + 2 * padding - k) // stride + 1

    def realize(sz):
        conv = _fl.conv2d_transpose if trans else _fl.conv2d
        out = conv(img, num_filters=num_filters, filter_size=filter_size,
                   stride=stride, padding=padding)
        # mixed_layer sums projections over a flat feature width
        return _fl.reshape(out, shape=[-1, num_filters * oh * ow])

    return _Projection(realize, width=num_filters * oh * ow)


def conv_operator(img, filter, filter_size, num_filters, num_channels=None,
                  stride=1, padding=0, trans=False, **kwargs):
    """reference conv_operator: convolve `img` with a COMPUTED filter
    tensor (not a parameter; e.g. another layer's output). Lowered by the
    conv2d_input_filter op — a vmapped XLA convolution so the per-sample
    filters still hit the MXU. trans=True is the transposed (deconv)
    form. Returns a mixed_layer projection whose flat width matches
    conv_projection's NCHW flatten."""
    k = int(filter_size)
    img4 = _as_nchw(img, num_channels)
    c = int(img4.shape[1])
    h, w = int(img4.shape[2]), int(img4.shape[3])
    if trans:
        oh = (h - 1) * stride - 2 * padding + k
        ow = (w - 1) * stride - 2 * padding + k
    else:
        oh = (h + 2 * padding - k) // stride + 1
        ow = (w + 2 * padding - k) // stride + 1

    def realize(sz):
        fil = _fl.reshape(filter, shape=[-1, num_filters, c, k, k])
        out = _raw_op("conv2d_input_filter", {"X": [img4], "Filter": [fil]},
                      {"stride": int(stride), "padding": int(padding),
                       "trans": bool(trans)})
        return _fl.reshape(out, shape=[-1, num_filters * oh * ow])

    return _Projection(realize, width=num_filters * oh * ow)


def block_expand_layer(input, block_x, block_y, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, num_channels=None,
                       **kwargs):
    """reference block_expand_layer -> fluid im2sequence (im2col as a
    sequence of flattened blocks, the OCR-CTC front end)."""
    return _fl.im2sequence(input, filter_size=[block_y, block_x],
                           stride=[stride_y, stride_x],
                           padding=[padding_y, padding_x])


def repeat_layer_as_seq(input, num_repeats, **kwargs):
    """alias used by some legacy configs; same as repeat_layer."""
    return repeat_layer(input, num_repeats)


def bilinear_interp_layer(input, out_size_x, out_size_y, **kwargs):
    """reference bilinear_interp_layer -> bilinear_interp op
    (jax.image.resize under the hood)."""
    return _raw_op("bilinear_interp", {"X": [input]},
                   {"out_h": int(out_size_y), "out_w": int(out_size_x)})


def sampling_id_layer(input, **kwargs):
    """reference sampling_id_layer -> sampling_id op: sample one id per
    row from the input's (normalized) distribution."""
    return _raw_op("sampling_id", {"X": [input]}, dtype="int64")


def hsigmoid(input, label, num_classes=None, param_attr=None,
             bias_attr=None, **kwargs):
    """reference hsigmoid (trainer_config_helpers/layers.py:2423):
    hierarchical sigmoid cost over a complete binary class tree."""
    if num_classes is None:
        t = getattr(label, "_v2_type", None)
        if t is None or t.kind != "int":
            raise ValueError("hsigmoid needs num_classes= or an integer "
                             "label from v2.layer.data")
        num_classes = t.dim
    return _fl.hsigmoid(input, label, num_classes, param_attr=param_attr,
                        bias_attr=bias_attr)


def conv_shift_layer(a, b, **kwargs):
    """reference conv_shift_layer (layers.py:5066): circular correlation
    c[i] = sum_j a[(i+j) mod M] * b[j], b's width odd."""
    return _raw_op("conv_shift", {"X": [a], "Y": [b]})


def gru_step_naive_layer(input, output_mem, size=None, **kwargs):
    """reference gru_step_naive_layer: same math as gru_step_layer
    without the fused-kernel layout constraint — identical here, where
    XLA does the fusing."""
    return gru_step_layer(input, output_mem, size=size, **kwargs)


def printer_layer(input, format=None, **kwargs):
    """reference printer_layer: print the input tensor at run time
    (maps to the fluid Print op, forward direction)."""
    return print_layer(input, **kwargs)


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, **kwargs):
    """reference lambda_cost (layers.py): LambdaRank listwise ranking
    cost — per query, |dNDCG@N|-weighted logistic loss over doc pairs.
    `max_sort_size` is accepted for API parity (the full pairwise form
    here subsumes the reference's partial-sort optimization)."""
    from ..fluid.layers.sequence import seq_lengths_of

    inputs = {"X": [input], "Score": [score]}
    lens = seq_lengths_of(input) or seq_lengths_of(score)
    if lens is not None:
        inputs["Lengths"] = [lens]
    return _raw_op("lambda_cost", inputs, {"NDCG_num": NDCG_num},
                   out_slots=("Cost",))


def scale_sub_region_layer(input, indices, value, num_channels=None,
                           **kwargs):
    """reference scale_sub_region_layer: scale a per-sample
    [c0:c1, h0:h1, w0:w1] box (1-based inclusive) by `value`."""
    return _raw_op("scale_sub_region",
                   {"X": [_as_nchw(input, num_channels)],
                    "Indices": [indices]},
                   {"value": float(value)})


class GeneratedInput:
    """reference paddle.layer.GeneratedInput: marks the decoder input that
    feeds back the previously generated token through an embedding."""

    def __init__(self, size, embedding_name, embedding_size, **kwargs):
        self.size = size                      # vocabulary size
        self.embedding_name = embedding_name  # shared with training
        self.embedding_size = embedding_size


class BeamMemory:
    """Recurrent-state spec for beam_search (declared OUTSIDE the loop —
    the generation While carries state arrays created before the block;
    an in-step memory() declaration could not be loop-carried). One of:
    boot_layer= (encoder-derived init, [B, H]) or size= (zero init)."""

    def __init__(self, boot_layer=None, size=None):
        if boot_layer is None and size is None:
            raise ValueError("BeamMemory needs boot_layer= or size=")
        self.boot_layer = boot_layer
        self.size = size


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=100,
                batch_size=None, memories=(), **kwargs):
    """reference paddle.layer.beam_search (generation over a recurrent
    step). `input` mixes ONE GeneratedInput (the fed-back token) with any
    number of StaticInput layers. Step contract here (documented
    divergence from the config-parser's name-linked in-step memories —
    loop state must pre-exist the While block to be carried):

      * recurrent state is declared up front via `memories=[BeamMemory
        (boot_layer=...), ...]`;
      * the step receives (token_emb, *statics, *memory_values) with
        beams FLATTENED into the batch dim — every tensor is
        [B*K, ...]; StaticInput layers are tiled over beams;
      * the step returns (prob, *new_memory_values): vocabulary
        probabilities plus one update per declared memory, in order.
        Selected beams' memories are reordered by parent via
        batch_gather each step.

    Returns (ids, scores) from beam_search_decode: ids is
    [B, beam, T+1] — ALL beams, best first, bos prefix included —
    and scores the matching per-beam totals. `batch_size` must be
    static (generation lanes are a [batch, beam] shape under XLA)."""
    ins = input if isinstance(input, (list, tuple)) else [input]
    gens = [x for x in ins if isinstance(x, GeneratedInput)]
    if len(gens) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    gen = gens[0]
    statics = [x.input if isinstance(x, StaticInput) else x
               for x in ins if not isinstance(x, GeneratedInput)]
    if batch_size is None:
        raise ValueError(
            "beam_search(batch_size=...) is required: generation lanes "
            "are a static [batch, beam] shape under XLA")
    B, K, V = int(batch_size), int(beam_size), int(gen.size)

    from ..fluid.layers import tensor as _t

    def _tile_over_beams(v):
        """[B, ...] -> [B*K, ...]: every beam lane sees the same static."""
        tail = [int(d) for d in v.shape[1:]]
        r = _fl.reshape(v, shape=[B, 1] + tail)
        r = _fl.expand(r, expand_times=[1, K] + [1] * len(tail))
        return _fl.reshape(r, shape=[B * K] + tail)

    statics = [_tile_over_beams(s) for s in statics]

    counter = _fl.zeros(shape=[1], dtype="int64")
    limit = _fl.fill_constant(shape=[1], dtype="int64", value=max_length)
    ids_arr = _fl.create_array("int64", max_length + 1, [B, K])
    scores_arr = _fl.create_array("float32", max_length + 1, [B, K])
    parents_arr = _fl.create_array("int32", max_length + 1, [B, K])

    init_ids = _fl.fill_constant(shape=[B, K], dtype="int64", value=bos_id)
    # lane 0 active, lanes 1.. start at -inf so step 1 expands ONE beam
    neg = _fl.fill_constant(shape=[B, K - 1], dtype="float32", value=-1e9) \
        if K > 1 else None
    zero = _fl.fill_constant(shape=[B, 1], dtype="float32", value=0.0)
    init_scores = _t.concat([zero, neg], axis=1) if neg is not None else zero
    _fl.array_write(init_ids, counter, ids_arr)
    _fl.array_write(init_scores, counter, scores_arr)

    # beam-tracked memories: arrays created (and booted) BEFORE the loop
    # so the While op carries them
    mem_arrays, mem_widths = [], []
    for m in memories:
        if m.boot_layer is not None:
            h = int(m.boot_layer.shape[-1])
            boot3 = _fl.reshape(m.boot_layer, shape=[B, 1, h])
            boot3 = _fl.expand(boot3, expand_times=[1, K, 1])
        else:
            h = int(m.size)
            boot3 = _fl.fill_constant(shape=[B, K, h], dtype="float32",
                                      value=0.0)
        arr = _fl.create_array("float32", max_length + 1, [B, K, h])
        _fl.array_write(boot3, counter, arr)
        mem_arrays.append(arr)
        mem_widths.append(h)

    cond = _fl.less_than(counter, limit)
    w = _fl.While(cond)
    with w.block():
        pre_ids = _fl.array_read(ids_arr, counter)
        pre_scores = _fl.array_read(scores_arr, counter)
        emb = _fl.embedding(
            pre_ids, size=[V, gen.embedding_size],
            param_attr=_ParamAttr(name=gen.embedding_name))  # [B, K, E]
        emb_flat = _fl.reshape(emb, shape=[B * K, gen.embedding_size])
        pre_mems = [
            _fl.reshape(_fl.array_read(arr, counter), shape=[B * K, h])
            for arr, h in zip(mem_arrays, mem_widths)
        ]

        outs = step(emb_flat, *statics, *pre_mems)
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        prob, new_mems = outs[0], outs[1:]
        if len(new_mems) != len(mem_arrays):
            raise ValueError(
                f"beam_search step returned {len(new_mems)} memory updates "
                f"for {len(mem_arrays)} declared memories")

        logp = _raw_op("log", {"X": [prob]})
        logp3 = _fl.reshape(logp, shape=[B, K, V])
        sel_ids, sel_scores, parent = _fl.beam_search(
            pre_ids, pre_scores, logp3, K, end_id=eos_id)
        _fl.increment(counter, value=1)
        _fl.array_write(sel_ids, counter, ids_arr)
        _fl.array_write(sel_scores, counter, scores_arr)
        _fl.array_write(parent, counter, parents_arr)
        for arr, new, h in zip(mem_arrays, new_mems, mem_widths):
            new3 = _fl.reshape(new, shape=[B, K, h])
            _fl.array_write(_fl.batch_gather(new3, parent), counter, arr)
        _fl.less_than(counter, limit, cond=cond)

    return _fl.beam_search_decode(ids_arr, scores_arr, parents_arr,
                                  end_id=eos_id)
