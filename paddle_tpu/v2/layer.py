"""v2 layer DSL mapped onto fluid layers (reference python/paddle/v2/layer.py
+ trainer_config_helpers/layers.py — declarative layers composed by passing
outputs as inputs). Each function appends ops to the implicit default
program, exactly like fluid layers; the v2-specific `data_type` objects
translate to fluid data vars."""
from __future__ import annotations

from ..fluid import layers as _fl


class _DataType:
    def __init__(self, kind: str, dim: int, seq: bool = False):
        self.kind = kind
        self.dim = dim
        self.seq = seq


class data_type:
    """reference paddle.v2.data_type."""

    @staticmethod
    def dense_vector(dim):
        return _DataType("dense", dim)

    @staticmethod
    def integer_value(dim):
        return _DataType("int", dim)

    @staticmethod
    def integer_value_sequence(dim):
        return _DataType("int", dim, seq=True)

    @staticmethod
    def dense_vector_sequence(dim):
        return _DataType("dense", dim, seq=True)


def data(name, type: _DataType, **kwargs):
    if type.kind == "int":
        shape = [1]
        dtype = "int64"
    else:
        shape = [type.dim]
        dtype = "float32"
    var = _fl.data(name=name, shape=shape, dtype=dtype, **kwargs)
    var._v2_type = type  # embedding_layer sizes its table from this
    return var


def fc_layer(input, size, act=None, **kwargs):
    return _fl.fc(input=input, size=size, act=_act_name(act), **kwargs)


def embedding_layer(input, size, vocab_size=None, **kwargs):
    """Table rows come from the input data layer's declared integer dim
    (reference: the v2 config carries the vocab through the data type)."""
    if vocab_size is None:
        t = getattr(input, "_v2_type", None)
        if t is None or t.kind != "int":
            raise ValueError(
                "embedding_layer needs vocab_size= or an input created by "
                "v2.layer.data with integer_value(_sequence)(dim)"
            )
        vocab_size = t.dim
    return _fl.embedding(input, size=[vocab_size, size], **kwargs)


def classification_cost(input, label):
    return _fl.mean(_fl.cross_entropy(input=input, label=label))


def square_error_cost(input, label):
    return _fl.mean(_fl.square_error_cost(input=input, label=label))


def cross_entropy_cost(input, label):
    return classification_cost(input, label)


def _concat(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.concat(input, **kwargs)


concat_layer = _concat


# --- activation / pooling namespaces (reference trainer_config_helpers
# activations.py / poolings.py: layer args take ReluActivation() /
# MaxPooling() instances) ---------------------------------------------------


class _Act:
    def __init__(self, name):
        self.name = name


class activation:
    """reference paddle.v2.activation.*"""

    Relu = staticmethod(lambda: _Act("relu"))
    Sigmoid = staticmethod(lambda: _Act("sigmoid"))
    Tanh = staticmethod(lambda: _Act("tanh"))
    Softmax = staticmethod(lambda: _Act("softmax"))
    Linear = staticmethod(lambda: _Act(None))
    Identity = staticmethod(lambda: _Act(None))


class _Pool:
    def __init__(self, kind):
        self.kind = kind


class pooling:
    """reference paddle.v2.pooling.* (sequence poolings)."""

    Max = staticmethod(lambda: _Pool("max"))
    Avg = staticmethod(lambda: _Pool("average"))
    Sum = staticmethod(lambda: _Pool("sum"))
    SquareRootN = staticmethod(lambda: _Pool("sqrt"))


def _act_name(act):
    return act.name if isinstance(act, _Act) else act


# --- sequence layers (reference trainer_config_helpers/layers.py:
# last_seq, first_seq, pooling_layer, lstmemory, grumemory, simple_lstm,
# simple_gru, expand_layer) -------------------------------------------------


def last_seq(input, **kwargs):
    return _fl.sequence_last_step(input)


def first_seq(input, **kwargs):
    return _fl.sequence_first_step(input)


def pooling_layer(input, pooling_type=None, **kwargs):
    """Sequence pooling (reference pooling_layer) — NOT image pooling
    (that's img_pool_layer)."""
    kind = pooling_type.kind if isinstance(pooling_type, _Pool) else (
        pooling_type or "max")
    return _fl.sequence_pool(input=input, pool_type=kind)


def lstmemory(input, size=None, reverse=False, act=None, **kwargs):
    """reference lstmemory: `size` is the HIDDEN width; the input must
    carry 4*size projected features (pair with fc_layer, as
    trainer_config_helpers documents). Default size = input_width // 4."""
    width = int(input.shape[-1])
    if size is None:
        size = width // 4
    if width != size * 4:
        raise ValueError(
            f"lstmemory(size={size}) needs an input of width {size * 4} "
            f"(4*size projected features), got {width}")
    h, _ = _fl.dynamic_lstm(input=input, size=size * 4, is_reverse=reverse)
    return h


def simple_lstm(input, size, reverse=False, **kwargs):
    """reference networks.simple_lstm: fc projection + lstmemory."""
    proj = _fl.fc(input=input, size=size * 4, num_flatten_dims=2)
    h, _ = _fl.dynamic_lstm(input=proj, size=size * 4, is_reverse=reverse)
    return h


def grumemory(input, size=None, reverse=False, **kwargs):
    """`size` is the hidden width; input carries 3*size projected gates."""
    width = int(input.shape[-1])
    if size is None:
        size = width // 3
    if width != size * 3:
        raise ValueError(
            f"grumemory(size={size}) needs an input of width {size * 3} "
            f"(3*size projected gates), got {width}")
    return _fl.dynamic_gru(input=input, size=size, is_reverse=reverse)


def simple_gru(input, size, reverse=False, **kwargs):
    proj = _fl.fc(input=input, size=size * 3, num_flatten_dims=2)
    return _fl.dynamic_gru(input=proj, size=size, is_reverse=reverse)


def expand_layer(input, expand_as, **kwargs):
    return _fl.sequence_expand(input, expand_as)


# --- image layers (reference img_conv_layer / img_pool_layer /
# simple_img_conv_pool) -----------------------------------------------------


def img_conv_layer(input, filter_size, num_filters, stride=1, padding=0,
                   act=None, **kwargs):
    return _fl.conv2d(input=input, num_filters=num_filters,
                      filter_size=filter_size, stride=stride,
                      padding=padding, act=_act_name(act))


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   **kwargs):
    kind = pool_type.kind if isinstance(pool_type, _Pool) else (
        pool_type or "max")
    if kind not in ("max", "avg", "average"):
        kind = "max"
    return _fl.pool2d(input=input, pool_size=pool_size, pool_stride=stride,
                      pool_padding=padding,
                      pool_type="avg" if kind != "max" else "max")


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    from ..fluid import nets as _nets

    return _nets.simple_img_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride, act=_act_name(act))


# --- elementwise / misc layers --------------------------------------------


def addto_layer(input, act=None, **kwargs):
    from ..fluid.layers import tensor as _t

    out = _t.sums(list(input))
    name = _act_name(act)
    if name:
        out = getattr(_fl, name)(out)
    return out


def cos_sim(a, b, **kwargs):
    return _fl.cos_sim(X=a, Y=b)


def scaling_layer(input, weight, **kwargs):
    return _fl.elementwise_mul(input, weight)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, **kwargs):
    return _fl.scale(input, scale=float(slope), bias=float(intercept))


def trans_layer(input, **kwargs):
    return _fl.transpose(input, perm=[1, 0])


def maxid_layer(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.argmax(input, axis=-1)


def dropout_layer(input, dropout_rate, **kwargs):
    return _fl.dropout(input, dropout_prob=dropout_rate)


batch_norm_layer = _fl.batch_norm
conv_layer = img_conv_layer


# --- cost layers (reference classification_cost / regression_cost /
# crf_layer / ctc_layer / rank_cost) ---------------------------------------


def regression_cost(input, label, **kwargs):
    return square_error_cost(input, label)


def mse_cost(input, label, **kwargs):
    return square_error_cost(input, label)


def crf_layer(input, label, param_attr=None, **kwargs):
    return _fl.linear_chain_crf(input=input, label=label,
                                param_attr=param_attr)


def crf_decoding_layer(input, param_attr, label=None, **kwargs):
    return _fl.crf_decoding(input=input, param_attr=param_attr, label=label)


def softmax_layer(input, **kwargs):
    return _fl.softmax(input)


# --- helper: append a raw op through the fluid LayerHelper ----------------


def _raw_op(op_type, inputs, attrs=None, n_outs=1, dtype=None,
            out_slots=("Out",)):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper(op_type)
    first = next(iter(inputs.values()))
    ref = first[0] if isinstance(first, (list, tuple)) else first
    dtype = dtype or ref.dtype
    outs = [helper.create_variable_for_type_inference(dtype)
            for _ in range(n_outs)]
    helper.append_op(
        type=op_type, inputs=inputs,
        outputs={slot: [o] for slot, o in zip(out_slots, outs)},
        attrs=attrs or {},
    )
    return outs[0] if n_outs == 1 else tuple(outs)


# --- mixed_layer projections / operators (reference
# trainer_config_helpers/layers.py: full_matrix_projection:...,
# identity_projection, table_projection, dotmul_projection,
# context_projection, dotmul_operator). A projection is a deferred spec;
# mixed_layer realizes each against its own `size` and sums them. --------


class _Projection:
    def __init__(self, realize):
        self.realize = realize  # size -> Variable


def full_matrix_projection(input, size=None, **kwargs):
    def realize(sz):
        # sequence inputs ([N, T, D]) project per-timestep
        flat = 2 if input.shape is not None and len(input.shape) == 3 else 1
        return _fl.fc(input=input, size=sz, act=None, num_flatten_dims=flat)

    return _Projection(realize)


def identity_projection(input, offset=None, **kwargs):
    def realize(sz):
        if offset is not None:
            return _raw_op("slice", {"Input": [input]},
                           {"axes": [input.ndim - 1 if hasattr(input, "ndim")
                                     else len(input.shape) - 1],
                            "starts": [offset], "ends": [offset + sz]})
        return input

    return _Projection(realize)


def table_projection(input, size=None, **kwargs):
    t = getattr(input, "_v2_type", None)
    vocab = t.dim if t is not None else None

    def realize(sz):
        if vocab is None:
            raise ValueError("table_projection input needs a v2 data type")
        return _fl.embedding(input, size=[vocab, sz])

    return _Projection(realize)


def dotmul_projection(input, **kwargs):
    def realize(sz):
        from ..fluid.layer_helper import LayerHelper

        helper = LayerHelper("dotmul_projection")
        w = helper.create_parameter(
            helper.param_attr, shape=[int(input.shape[-1])],
            dtype=input.dtype)
        return _fl.elementwise_mul(input, w)

    return _Projection(realize)


def context_projection(input, context_len=3, context_start=None, **kwargs):
    """Concat each timestep with its neighbours (reference
    context_projection -> math/context_project)."""
    def realize(sz):
        from ..fluid.layers.sequence import seq_lengths_of

        inputs = {"X": [input]}
        lens = seq_lengths_of(input)
        if lens is not None:
            inputs["Lengths"] = [lens]
        attrs = {"context_length": context_len}
        if context_start is not None:
            attrs["context_start"] = context_start
        return _raw_op("context_project", inputs, attrs)

    return _Projection(realize)


def dotmul_operator(a, b, scale=1.0, **kwargs):
    return _Projection(lambda sz: _fl.scale(_fl.elementwise_mul(a, b),
                                            scale=float(scale)))


def mixed_layer(*args, size=None, input=None, act=None, bias_attr=None,
                **kwargs):
    """reference mixed_layer: sum of realized projections/operators, then
    activation. Plain Variables act as full-matrix projections. Accepted
    call forms: mixed_layer(size=N, input=[...]) (reference kwargs),
    mixed_layer(inputs, N), and mixed_layer(inputs, size=N) (legacy
    positional input)."""
    for a in args:  # positional args: ints are size, everything else input
        if isinstance(a, int):
            size = a
        else:
            input = a
    if size is None:
        raise TypeError("mixed_layer needs an integer size")
    ins = input if isinstance(input, (list, tuple)) else [input]
    realized = []
    for p in ins:
        if isinstance(p, _Projection):
            realized.append(p.realize(size))
        else:
            realized.append(_fl.fc(input=p, size=size, act=None)
                            if size else p)
    out = realized[0]
    for r in realized[1:]:
        out = _fl.elementwise_add(out, r)
    name = _act_name(act)
    if name:
        out = getattr(_fl, name)(out)
    return out


# --- elementwise / arithmetic layers (reference layers.py interpolation,
# power, sum_to_one_norm, row_l2_norm, dot_prod, out_prod, linear_comb,
# l2_distance, clip, scale_shift, slope_intercept) ------------------------


def interpolation_layer(input, weight, **kwargs):
    """out = w*x + (1-w)*y with input=[x, y], per-row weight in [0,1]."""
    x, y = input
    wx = _fl.elementwise_mul(x, weight)
    one_minus = _fl.scale(weight, scale=-1.0, bias=1.0)
    wy = _fl.elementwise_mul(y, one_minus)
    return _fl.elementwise_add(wx, wy)


def power_layer(input, weight, **kwargs):
    return _raw_op("elementwise_pow", {"X": [input], "Y": [weight]})


def sum_to_one_norm_layer(input, **kwargs):
    s = _fl.reduce_sum(input, dim=-1, keep_dim=True)
    return _raw_op("elementwise_div", {"X": [input], "Y": [s]})


def row_l2_norm_layer(input, **kwargs):
    return _fl.l2_normalize(input, axis=-1)


def dot_prod_layer(a, b, **kwargs):
    return _fl.reduce_sum(_fl.elementwise_mul(a, b), dim=-1, keep_dim=True)


def out_prod_layer(a, b, **kwargs):
    """Per-row outer product flattened to [N, da*db] (reference
    out_prod_layer)."""
    da, db = int(a.shape[-1]), int(b.shape[-1])
    am = _fl.reshape(a, shape=[-1, da, 1])
    bm = _fl.reshape(b, shape=[-1, 1, db])
    return _fl.reshape(_fl.matmul(am, bm), shape=[-1, da * db])


def linear_comb_layer(weights, vectors, size, **kwargs):
    """Rowwise weighted sum of `size`-dim sub-vectors (reference
    linear_comb_layer): vectors [N, m*size] grouped by weights [N, m]."""
    m = int(weights.shape[-1])
    v = _fl.reshape(vectors, shape=[-1, m, size])
    w = _fl.reshape(weights, shape=[-1, m, 1])
    return _fl.reshape(_fl.reduce_sum(_fl.elementwise_mul(v, w), dim=1),
                       shape=[-1, size])


def l2_distance_layer(x, y, **kwargs):
    return _raw_op("squared_l2_distance", {"X": [x], "Y": [y]},
                   n_outs=2, out_slots=("Out", "sub_result"))[0]


def clip_layer(input, min, max, **kwargs):
    return _fl.clip(input, min=float(min), max=float(max))


def scale_shift_layer(input, **kwargs):
    """y = w*x + b with SCALAR learnable w, b (reference
    scale_shift_layer)."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("scale_shift")
    w = helper.create_parameter(helper.param_attr, shape=[1],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.param_attr, shape=[1],
                                dtype=input.dtype, is_bias=True)
    return _fl.elementwise_add(_fl.elementwise_mul(input, w), b)


def sum_cost(input, **kwargs):
    return _fl.reduce_sum(input)


# --- shape / image manipulation layers (reference repeat_layer, pad,
# crop, rotate, resize, maxout, spp, img_cmrnorm, roi_pool, bilinear) ------


def repeat_layer(input, num_repeats, **kwargs):
    times = [1] * (len(input.shape) - 1) + [int(num_repeats)]
    return _raw_op("expand", {"X": [input]}, {"expand_times": times})


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, **kwargs):
    """NCHW padding (reference pad_layer pads channel/height/width)."""
    paddings = [0, 0]
    for p in (pad_c, pad_h, pad_w):
        p = p or [0, 0]
        paddings += list(p)
    return _fl.pad(input, paddings=paddings)


def crop_layer(input, shape=None, offsets=None, **kwargs):
    attrs = {}
    if shape is not None:
        attrs["shape"] = list(shape)
    if offsets is not None:
        attrs["offsets"] = list(offsets)
    return _raw_op("crop", {"X": [input]}, attrs)


def rotate_layer(input, height, width, **kwargs):
    """90-degree CCW rotation of each feature map (reference rotate_layer:
    transpose H/W then reverse the new height axis)."""
    c = int(input.shape[1]) if len(input.shape) > 3 else 1
    x = _fl.reshape(input, shape=[-1, c, height, width])
    t = _fl.transpose(x, perm=[0, 1, 3, 2])
    return _raw_op("reverse", {"X": [t]}, {"axis": [2]})


def resize_layer(input, size, **kwargs):
    return _fl.reshape(input, shape=[-1, int(size)])


def maxout_layer(input, groups, **kwargs):
    return _raw_op("maxout", {"X": [input]}, {"groups": int(groups)})


def spp_layer(input, pyramid_height, pool_type=None, **kwargs):
    kind = pool_type.kind if isinstance(pool_type, _Pool) else (
        pool_type or "max")
    return _raw_op("spp", {"X": [input]},
                   {"pyramid_height": int(pyramid_height),
                    "pooling_type": "avg" if kind != "max" else "max"})


def img_cmrnorm_layer(input, size=5, scale=0.0128, power=0.75, **kwargs):
    """Local response norm across channels (reference img_cmrnorm_layer ->
    lrn op; alpha = scale/size per the config_parser translation)."""
    return _fl.lrn(input, n=int(size), alpha=float(scale) / int(size),
                   beta=float(power))


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale=1.0, **kwargs):
    return _raw_op("roi_pool", {"X": [input], "ROIs": [rois]},
                   {"pooled_height": int(pooled_height),
                    "pooled_width": int(pooled_width),
                    "spatial_scale": float(spatial_scale)})


def print_layer(input, **kwargs):
    from ..fluid.layers import tensor as _t

    return _t.Print(input) if hasattr(_t, "Print") else input


# --- sequence layers (reference seq_concat, seq_reshape, seq_slice,
# sub_seq via slice, context window via row_conv) --------------------------


def seq_concat_layer(a, b, **kwargs):
    return _fl.sequence_concat([a, b])


def seq_reshape_layer(input, reshape_size, **kwargs):
    return _fl.sequence_reshape(input, new_dim=int(reshape_size))


def seq_slice_layer(input, starts, ends, **kwargs):
    length = _fl.elementwise_sub(ends, starts)
    return _raw_op("sequence_slice",
                   {"X": [input], "Offset": [starts], "Length": [length]})


def row_conv_layer(input, context_len, **kwargs):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("row_conv")
    w = helper.create_parameter(
        helper.param_attr, shape=[int(context_len), int(input.shape[-1])],
        dtype=input.dtype)
    return _raw_op("row_conv", {"X": [input], "Filter": [w]})


# --- recurrent step layers (reference gru_step_layer, lstm_step_layer) ----


def gru_step_layer(input, output_mem, size=None, **kwargs):
    size = size or int(output_mem.shape[-1])
    from ..fluid.layers import sequence as _seq

    h, _, _ = _seq.gru_unit(input=input, hidden=output_mem, size=size * 3)
    return h


def lstm_step_layer(input, state, size=None, **kwargs):
    """One LSTM step (reference lstm_step_layer): input carries 4*size
    gates; state is the previous cell. Returns (hidden, new_cell)."""
    size = size or int(state.shape[-1])
    c, h = _raw_op("lstm_unit", {"X": [input], "C_prev": [state]},
                   n_outs=2, out_slots=("C", "H"))
    return h, c


# --- cost layers ----------------------------------------------------------


def rank_cost(left, right, label, **kwargs):
    return _fl.mean(_raw_op("rank_loss",
                            {"Left": [left], "Right": [right],
                             "Label": [label]}))


def huber_regression_cost(input, label, delta=1.0, **kwargs):
    return _fl.mean(_raw_op("huber_loss", {"X": [input], "Y": [label]},
                            {"delta": float(delta)}, n_outs=2,
                            out_slots=("Out", "Residual"))[0])


def huber_classification_cost(input, label, **kwargs):
    """reference huber_classification_cost (modified huber on +-1
    labels)."""
    return _fl.mean(_raw_op("modified_huber_loss",
                            {"X": [input], "Y": [label]}, n_outs=2,
                            out_slots=("Out", "IntermediateVal"))[0])


def multi_binary_label_cross_entropy(input, label, **kwargs):
    return _fl.mean(_fl.sigmoid_cross_entropy_with_logits(x=input,
                                                          label=label))


def smooth_l1_cost(input, label, **kwargs):
    return _fl.mean(_fl.smooth_l1(x=input, y=label))


def nce_layer(input, label, num_classes, num_neg_samples=10, **kwargs):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("nce_layer")
    dim = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[int(num_classes), dim],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.param_attr,
                                shape=[int(num_classes)],
                                dtype=input.dtype, is_bias=True)
    return _fl.mean(_raw_op(
        "nce", {"Input": [input], "Label": [label], "Weight": [w],
                "Bias": [b]},
        {"num_total_classes": int(num_classes),
         "num_neg_samples": int(num_neg_samples)},
        n_outs=3, out_slots=("Cost", "SampleLogits", "SampleLabels"))[0])


def ctc_layer(input, label, blank=0, **kwargs):
    return _fl.mean(_raw_op("warpctc", {"Logits": [input],
                                        "Label": [label]},
                            {"blank": int(blank)},
                            out_slots=("Loss",)))


warp_ctc_layer = ctc_layer


# --- recurrent_group (reference trainer_config_helpers recurrent_group +
# memory + StaticInput — the legacy DSL's custom-RNN API, backed here by
# fluid's DynamicRNN masked-scan lowering) ---------------------------------


class StaticInput:
    """Non-sequence input visible at every step (reference
    paddle.layer.StaticInput)."""

    def __init__(self, input, **kwargs):
        self.input = input


_current_group = None


def memory(name=None, size=None, boot_layer=None, **kwargs):
    """Declare a recurrent state inside a recurrent_group step (reference
    paddle.layer.memory): returns the PREVIOUS step's value. The state the
    step returns (single-memory form) or the returned output whose
    position matches the memory's declaration order feeds the next step."""
    if _current_group is None:
        raise RuntimeError("memory() is only valid inside a "
                           "recurrent_group step function")
    return _current_group._declare_memory(name, size, boot_layer)


class _GroupCtx:
    def __init__(self, drnn):
        self.drnn = drnn
        self.declared = []  # pre-mem vars, in declaration order

    def _declare_memory(self, name, size, boot_layer):
        if boot_layer is not None:
            pre = self.drnn.memory(init=boot_layer)
        elif size is not None:
            pre = self.drnn.memory(shape=[int(size)], value=0.0)
        else:
            raise ValueError(
                "memory() requires size= or boot_layer= (the reference's "
                "link-by-name form resolves sizes from the parsed config; "
                "here the state width must be explicit)")
        self.declared.append(pre)
        return pre


def recurrent_group(step, input, reverse=False, **kwargs):
    """reference recurrent_group: run `step` once per timestep over the
    sequence input(s); memories declared via layer.memory carry state.
    The step's outputs update the memories in declaration order (the
    single-memory/single-output form is the reference's dominant usage);
    extra outputs beyond the declared memories are emitted only.
    reverse=True is not supported by the masked-scan lowering — reverse
    the sequence with the `reverse` op (or use simple_lstm(reverse=True))
    instead."""
    global _current_group

    if reverse:
        raise NotImplementedError(
            "recurrent_group(reverse=True): reverse the input sequence "
            "instead (layers.reverse / simple_lstm(reverse=True))")
    from ..fluid.layers.control_flow import DynamicRNN

    ins = input if isinstance(input, (list, tuple)) else [input]
    drnn = DynamicRNN()
    prev = _current_group
    mismatch = None
    with drnn.block():
        step_args = []
        for x in ins:
            if isinstance(x, StaticInput):
                step_args.append(drnn.static_input(x.input))
            else:
                step_args.append(drnn.step_input(x))
        _current_group = _GroupCtx(drnn)
        step_exc = None
        try:
            outs = step(*step_args)
        except Exception as e:
            # a raw raise here would be shadowed by DynamicRNN._complete()
            # (block()'s finally asserts every memory updated) — self-link
            # the declared state so the USER's error survives the exit
            step_exc = e
            outs = []
        finally:
            ctx, _current_group = _current_group, prev
        if step_exc is not None:
            for mem in ctx.declared:
                drnn.update_memory(mem, mem)
            drnn.output(*(ctx.declared or step_args[:1]))
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        if ctx.declared and len(outs) < len(ctx.declared):
            # raising here would be shadowed by DynamicRNN._complete()'s
            # own invariant (block()'s finally) — still update what we can
            # so the clearer error below is the one the user sees
            mismatch = (len(outs), len(ctx.declared))
        if step_exc is None:
            for mem, out in zip(ctx.declared, outs):
                drnn.update_memory(mem, out)
            for mem in ctx.declared[len(outs):]:
                drnn.update_memory(mem, mem)  # satisfy the block invariant;
                # the ValueError below is the error the user actually sees
            drnn.output(*outs)
    if step_exc is not None:
        raise step_exc
    if mismatch is not None:
        raise ValueError(
            f"step returned {mismatch[0]} outputs but declared "
            f"{mismatch[1]} memories — each memory updates from the "
            "same-position output")
    return drnn()  # DynamicRNN() unwraps a single output itself


def recurrent_layer(input, act=None, reverse=False, **kwargs):
    """Simple Elman recurrence (reference recurrent_layer):
    h_t = act(x_t + W h_{t-1}) — the input carries the ALREADY-projected
    x, so only the recurrent weight W is learned here (pair with fc_layer
    for the input projection, as the legacy configs do)."""
    size = int(input.shape[-1])
    # default act is tanh (reference recurrent_layer); an EXPLICIT
    # Linear()/Identity() activation means no nonlinearity, not tanh
    act_name = "tanh" if act is None else _act_name(act)

    def step(x_t):
        h_prev = memory(size=size)
        rec = _fl.fc(input=h_prev, size=size, act=None)
        h = _fl.elementwise_add(x_t, rec)
        if act_name:
            h = getattr(_fl, act_name)(h)
        return h

    return recurrent_group(step=step, input=input, reverse=reverse)
