"""v2 Topology: the serialized-model-graph object (reference
python/paddle/v2/topology.py — wraps the ModelConfig proto built by
trainer/config_parser.py from the layer DSL; v2 ships it to trainers and
serializes it with parameters for inference).

Here the "config proto" is the fluid ProgramDesc the DSL built: Topology
prunes the default main program to the requested output layers (dropping
cost/backward/optimizer ops — the reference's serialize_for_inference
contract), serializes it (proto.py byte format) with the feed/fetch
metadata, and round-trips back to an executable inference program.
"""
from __future__ import annotations

import json
from typing import List

from .. import fluid
from ..fluid.framework import Program, Variable
from ..fluid.io import _prune_for_inference
from .trainer import _data_var_names


class Topology:
    def __init__(self, layers, extra_layers=None):
        if isinstance(layers, Variable):
            layers = [layers]
        self.layers: List[Variable] = list(layers)
        if extra_layers:
            self.layers += list(extra_layers)
        # prune to the output layers: the shipped graph is inference-only
        # even when the builder's default program has grown cost/optimizer
        # ops (reference serialize_for_inference)
        self.main_program = _prune_for_inference(
            fluid.default_main_program(), [], self.layers)
        self.startup_program = fluid.default_startup_program()
        self.layers = [self.main_program.global_block().var(v.name)
                       for v in self.layers]

    # -- introspection (reference Topology.get_layer / data_layers) -------
    def output_names(self) -> List[str]:
        return [v.name for v in self.layers]

    def data_names(self) -> List[str]:
        return _data_var_names(self.main_program.global_block())

    # -- serialization (reference Topology.serialize_for_inference) -------
    def serialize(self) -> bytes:
        meta = {
            "output_names": self.output_names(),
            "data_names": self.data_names(),
        }
        blob = {
            "meta": meta,
            "main_hex": self.main_program.to_bytes().hex(),
            "startup_hex": self.startup_program.to_bytes().hex(),
        }
        return json.dumps(blob).encode("utf-8")

    def serialize_for_inference(self, stream):
        stream.write(self.serialize())

    @classmethod
    def deserialize(cls, data: bytes) -> "Topology":
        blob = json.loads(data.decode("utf-8"))
        topo = cls.__new__(cls)
        topo.main_program = Program.parse_from_bytes(
            bytes.fromhex(blob["main_hex"]))
        topo.startup_program = Program.parse_from_bytes(
            bytes.fromhex(blob["startup_hex"]))
        block = topo.main_program.global_block()
        topo.layers = [block.var(n) for n in blob["meta"]["output_names"]]
        return topo

    def proto(self):
        """The underlying serializable desc (reference returns the
        ModelConfig protobuf)."""
        return self.main_program.desc
