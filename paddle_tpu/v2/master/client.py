"""reference python/paddle/v2/master/client.py:29 — the trainer-side
master client (set_dataset / next_record task loop)."""
from __future__ import annotations

import queue
import threading


class _Pump:
    """Background record prefetcher over a BOUNDED queue (role of the Go
    client's buffered record channel). The queue's maxsize is the
    backpressure: the thread blocks once `buf_size` records wait, so a
    slow trainer never buffers a whole pass in memory.

    Termination protocol: on natural end of pass, _EOP is enqueued (after
    an error, too — with the error kept for the consumer to re-raise). On
    stop(), the pump exits at the next queue-put, closing the records
    generator so the in-flight task lease is RELEASED to the master
    immediately rather than expiring."""

    _EOP = object()

    def __init__(self, records_fn, buf_size: int):
        self.q: "queue.Queue" = queue.Queue(maxsize=buf_size)
        self.stop = threading.Event()
        self.err = None
        self.exhausted = False
        self._gen = records_fn(should_stop=self.stop.is_set)
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        stopped = False
        try:
            for rec in self._gen:
                placed = False
                while not self.stop.is_set():
                    try:
                        self.q.put(rec, timeout=0.1)
                        placed = True
                        break
                    except queue.Full:
                        pass
                if not placed:
                    stopped = True
                    return
        except Exception as e:
            # keep it: a reader error must surface from next_record(), not
            # vanish with the daemon thread (it would read as end-of-pass)
            self.err = e
        finally:
            if stopped:
                try:
                    # releases the in-flight task lease (records() handles
                    # GeneratorExit with task_released)
                    self._gen.close()
                except Exception:
                    pass
            else:
                while not self.stop.is_set():
                    try:
                        self.q.put(_Pump._EOP, timeout=0.1)
                        break
                    except queue.Full:
                        pass

    def retire(self):
        """Stop the pump and discard whatever it already buffered. Cheap:
        the stop flag exits the pump at its next put, it does NOT stream
        the rest of the pass just to throw it away."""
        self.stop.set()
        while self.thread.is_alive():
            try:
                self.q.get(timeout=0.05)
            except queue.Empty:
                pass
        self.thread.join()


class client:
    """API-parity facade over distributed.master.MasterClient. The
    reference dials etcd to find the Go master; here `endpoints` is the
    master's own "host:port" (or (host, port)).

    `timeout_sec` and `buf_size` carry the reference ctypes client's
    semantics (client.py:25): timeout_sec is the dial + per-RPC deadline;
    buf_size > 0 prefetches up to that many records into a bounded queue
    from a background thread, overlapping record fetch with training
    compute."""

    def __init__(self, endpoints, timeout_sec: int = 5, buf_size: int = 0):
        from ...distributed.master import MasterClient

        self._client = MasterClient(addr=endpoints,
                                    timeout=float(timeout_sec) or None)
        self._buf_size = int(buf_size)
        self._records = None
        self._pump = None

    def _retire_pump(self):
        if self._pump is not None:
            self._pump.retire()
            self._pump = None

    def _start_pass(self):
        if self._buf_size > 0:
            self._pump = _Pump(self._client.records, self._buf_size)
        else:
            self._records = self._client.records()

    def set_dataset(self, paths):
        # a still-running pump from a previous dataset would keep leasing
        # (and discarding) tasks of the NEW dataset — stop it first
        self._retire_pump()
        self._client.set_dataset(list(paths))
        self._start_pass()

    def next_record(self):
        """One record (bytes), or None at end of pass (the reference's
        (None, -1) end condition collapsed to None; like the unbuffered
        path, repeated calls after the end keep returning None)."""
        if self._pump is not None:
            if self._pump.exhausted:
                return None
            rec = self._pump.q.get()
            if rec is _Pump._EOP:
                self._pump.exhausted = True
                if self._pump.err is not None:
                    raise self._pump.err
                return None
            return rec
        if self._records is None:
            raise RuntimeError("set_dataset() first")
        try:
            return next(self._records)
        except StopIteration:
            return None

    def paddle_start_get_records(self, pass_id):  # reference client.py:94
        self._retire_pump()
        if self._client.all_done():
            # previous pass fully consumed: re-queue its tasks (the Go
            # master rolls passes inside TaskFinished; this service makes
            # the roll explicit so all_done() can mark pass ends). When
            # records were abandoned mid-pass, their released leases are
            # back in todo and the CURRENT pass simply continues.
            self._client.new_pass()
        self._start_pass()

    def request_save_model(self, trainer_id, block_ms):
        """The reference asks the master which ONE trainer should save the
        model this pass; with the TCP master any caller may save — report
        yes for trainer 0, matching the single-writer intent."""
        return 1 if int(trainer_id) == 0 else 0

    def release(self):
        self._retire_pump()
        self._client.close()
