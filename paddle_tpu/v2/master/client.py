"""reference python/paddle/v2/master/client.py:29 — the trainer-side
master client (set_dataset / next_record task loop)."""
from __future__ import annotations


class client:
    """API-parity facade over distributed.master.MasterClient. The
    reference dials etcd to find the Go master; here `endpoints` is the
    master's own "host:port" (or (host, port))."""

    def __init__(self, endpoints, timeout_sec: int = 5, buf_size: int = 0):
        from ...distributed.master import MasterClient

        self._client = MasterClient(addr=endpoints)
        self._records = None

    def set_dataset(self, paths):
        self._client.set_dataset(list(paths))
        self._records = self._client.records()

    def next_record(self):
        """One record (bytes), or None at end of pass (the reference's
        (None, -1) end condition collapsed to None)."""
        if self._records is None:
            raise RuntimeError("set_dataset() first")
        try:
            return next(self._records)
        except StopIteration:
            return None

    def paddle_start_get_records(self, pass_id):  # reference client.py:94
        if self._client.all_done():
            # previous pass fully consumed: re-queue its tasks (the Go
            # master rolls passes inside TaskFinished; this service makes
            # the roll explicit so all_done() can mark pass ends)
            self._client.new_pass()
        self._records = self._client.records()

    def request_save_model(self, trainer_id, block_ms):
        """The reference asks the master which ONE trainer should save the
        model this pass; with the TCP master any caller may save — report
        yes for trainer 0, matching the single-writer intent."""
        return 1 if int(trainer_id) == 0 else 0

    def release(self):
        self._client.close()
