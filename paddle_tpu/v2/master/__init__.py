"""reference python/paddle/v2/master/: ctypes client onto the Go master's
C shared library. Here the same surface fronts distributed.master's TCP
MasterClient — no C library, no etcd; the endpoint is the master's
host:port."""
from .client import client  # noqa: F401
