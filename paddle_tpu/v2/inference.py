"""v2 inference (reference python/paddle/v2/inference.py paddle.infer)."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .. import fluid


def infer(output_layer, parameters, input, feeding: Optional[Dict] = None,
          field: str = "value"):
    """Run the pruned inference slice of the topology on `input` (a list of
    samples) and return the stacked outputs."""
    main = parameters.main_program
    infer_prog = fluid.io.get_inference_program([output_layer],
                                                main_program=main)
    block = infer_prog.global_block()
    needed = set()
    for op in block.ops:
        needed.update(n for n in op.desc.input_names() if n)
    from .trainer import _data_var_names

    feed_names = [n for n in _data_var_names(main.global_block())
                  if n in needed]
    if feeding is not None:
        order = sorted(feeding.items(), key=lambda kv: kv[1])
        feed_names = [n for n, _ in order if n in needed] or feed_names
    feeder = fluid.DataFeeder(
        place=None, feed_list=[main.global_block().var(n) for n in feed_names]
    )
    exe = fluid.Executor()
    with fluid.scope_guard(parameters.scope):
        (out,) = exe.run(infer_prog, feed=feeder.feed(input),
                         fetch_list=[output_layer])
    return np.asarray(out)
