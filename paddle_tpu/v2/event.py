"""Training events (reference python/paddle/v2/event.py)."""
from __future__ import annotations


class WithMetric:
    def __init__(self, evaluator=None):
        self.evaluator = evaluator


class BeginPass:
    def __init__(self, pass_id: int):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id: int, evaluator=None):
        super().__init__(evaluator)
        self.pass_id = pass_id


class BeginIteration:
    def __init__(self, pass_id: int, batch_id: int):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id: int, batch_id: int, cost: float,
                 evaluator=None):
        super().__init__(evaluator)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost


class TestResult(WithMetric):
    def __init__(self, cost: float, evaluator=None):
        super().__init__(evaluator)
        self.cost = cost
