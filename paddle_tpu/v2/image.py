"""Image preprocessing utilities (reference python/paddle/v2/image.py).

The reference uses cv2; here the transforms are pure numpy (HWC uint8 or
float arrays), so the hermetic environment needs no vision dependency.
`load_image` tries PIL then cv2 and raises a pointed error when neither
is available — decoding bytes is the only step that genuinely needs a
codec."""
from __future__ import annotations

import numpy as np

__all__ = [
    "load_image", "load_image_bytes", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def _resize_bilinear(im, h, w):
    """HWC (or HW) bilinear resize, align-corners=False (the cv2 default
    the reference relied on)."""
    im = np.asarray(im)
    src_h, src_w = im.shape[:2]
    if (src_h, src_w) == (h, w):
        return im.copy()
    ys = (np.arange(h) + 0.5) * src_h / h - 0.5
    xs = (np.arange(w) + 0.5) * src_w / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, src_h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, src_w - 1)
    y1 = np.clip(y0 + 1, 0, src_h - 1)
    x1 = np.clip(x0 + 1, 0, src_w - 1)
    fy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    fx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    if im.ndim == 3:
        fy, fx = fy[..., None], fx[..., None]
    a = im[y0][:, x0].astype(np.float64)
    b = im[y0][:, x1].astype(np.float64)
    c = im[y1][:, x0].astype(np.float64)
    d = im[y1][:, x1].astype(np.float64)
    out = (a * (1 - fy) * (1 - fx) + b * (1 - fy) * fx
           + c * fy * (1 - fx) + d * fy * fx)
    return out.astype(im.dtype) if np.issubdtype(im.dtype, np.integer) \
        else out.astype(im.dtype)


def load_image_bytes(bytes, is_color=True):  # noqa: A002 (reference name)
    """Decode an encoded image buffer. Needs PIL or cv2."""
    import io

    try:
        from PIL import Image

        img = Image.open(io.BytesIO(bytes))
        img = img.convert("RGB" if is_color else "L")
        return np.asarray(img)
    except ImportError:
        pass
    try:
        import cv2

        flag = 1 if is_color else 0
        arr = np.frombuffer(bytes, dtype=np.uint8)
        return cv2.imdecode(arr, flag)
    except ImportError:
        raise ImportError(
            "decoding image bytes needs PIL or cv2; the numpy-only "
            "transforms (resize/crop/flip) work on already-decoded arrays")


def load_image(file, is_color=True):  # noqa: A002
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color=is_color)


def resize_short(im, size):
    """Resize so the SHORT edge becomes `size`, keeping aspect ratio
    (reference image.py:163)."""
    h, w = im.shape[:2]
    if h > w:
        return _resize_bilinear(im, int(round(h * size / w)), size)
    return _resize_bilinear(im, size, int(round(w * size / h)))


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference image.py:189)."""
    return np.asarray(im).transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0, w0 = (h - size) // 2, (w - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, h - size + 1)
    w0 = np.random.randint(0, w - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short -> (random|center) crop (+ random flip when training)
    -> CHW float32 -> optional mean subtraction (reference image.py:291)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color=is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean if mean.ndim != 1 else mean[:, None, None]
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pickle batches of (encoded image, label) pairs out of a tar archive
    (reference image.py:48) — used by the legacy flowers pipeline."""
    import os
    import pickle
    import tarfile

    out_path = f"{data_file}.{dataset_name}.batch"
    meta = {"file_list": [], "num_samples": 0}
    if os.path.isdir(out_path):
        return out_path
    os.makedirs(out_path, exist_ok=True)
    data, labels, nfile = [], [], 0
    with tarfile.open(data_file) as tf:
        for member in tf.getmembers():
            if member.name not in img2label:
                continue
            data.append(tf.extractfile(member).read())
            labels.append(img2label[member.name])
            meta["num_samples"] += 1
            if len(data) == num_per_batch:
                name = os.path.join(out_path, f"batch_{nfile}")
                with open(name, "wb") as f:
                    pickle.dump({"data": data, "label": labels}, f,
                                protocol=2)
                meta["file_list"].append(name)
                data, labels, nfile = [], [], nfile + 1
    if data:
        name = os.path.join(out_path, f"batch_{nfile}")
        with open(name, "wb") as f:
            pickle.dump({"data": data, "label": labels}, f, protocol=2)
        meta["file_list"].append(name)
    with open(os.path.join(out_path, "batch_meta"), "wb") as f:
        pickle.dump(meta, f, protocol=2)
    return out_path
