"""v2 optimizers (reference python/paddle/v2/optimizer.py) — wrappers
binding fluid optimizers with v2 constructor names."""
from __future__ import annotations

from ..fluid import optimizer as _fopt


class _V2Optimizer:
    def __init__(self, fluid_opt):
        self.fluid_opt = fluid_opt


class Momentum(_V2Optimizer):
    def __init__(self, momentum=0.9, learning_rate=0.01, sparse=False,
                 regularization=None, **kwargs):
        super().__init__(_fopt.Momentum(learning_rate=learning_rate,
                                        momentum=momentum,
                                        regularization=regularization))


class Adam(_V2Optimizer):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, **kwargs):
        super().__init__(_fopt.Adam(learning_rate=learning_rate, beta1=beta1,
                                    beta2=beta2, epsilon=epsilon,
                                    regularization=regularization))


class AdaGrad(_V2Optimizer):
    def __init__(self, learning_rate=1e-2, regularization=None, **kwargs):
        super().__init__(_fopt.Adagrad(learning_rate=learning_rate,
                                       regularization=regularization))


class RMSProp(_V2Optimizer):
    def __init__(self, learning_rate=1e-2, rho=0.95, epsilon=1e-6,
                 regularization=None, **kwargs):
        super().__init__(_fopt.RMSProp(learning_rate=learning_rate, rho=rho,
                                       epsilon=epsilon,
                                       regularization=regularization))
