"""v2 Parameters (reference python/paddle/v2/parameters.py — numpy-backed
parameter pool with tar serialization)."""
from __future__ import annotations

import pickle
from typing import Dict

import numpy as np

from .. import fluid


class Parameters:
    """Holds the scope + programs behind a v2 topology."""

    def __init__(self, scope, main_program, startup_program):
        self.scope = scope
        self.main_program = main_program
        self.startup_program = startup_program

    def names(self):
        return [p.name for p in self.main_program.global_block().all_parameters()]

    def get(self, name) -> np.ndarray:
        return np.asarray(self.scope.find_var(name))

    def set(self, name, value):
        import jax.numpy as jnp

        self.scope.set_var(name, jnp.asarray(value))

    def __iter__(self):
        return iter(self.names())

    def to_tar(self, f):
        """reference to_tar — here a pickle of name->ndarray."""
        pickle.dump({n: self.get(n) for n in self.names()}, f,
                    protocol=pickle.HIGHEST_PROTOCOL)

    @classmethod
    def from_tar(cls, f, topology_cost=None):
        data: Dict[str, np.ndarray] = pickle.load(f)
        params = create(topology_cost)
        for n, v in data.items():
            params.set(n, v)
        return params


def create(cost=None) -> Parameters:
    """Materialize parameters for the current default programs (reference
    paddle.v2.parameters.create(cost)): runs the startup program into a
    fresh scope."""
    scope = fluid.global_scope()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return Parameters(scope, fluid.default_main_program(),
                      fluid.default_startup_program())
