"""`paddle_trainer`-style CLI over the v2 facade (reference
paddle/trainer/TrainerMain.cpp:32-53 + the legacy workflow: a Python config
file declares the network, the binary drives passes, logging, checkpoints).

Usage:
    python -m paddle_tpu.v2.trainer_cli --config my_config.py \
        --num-passes 3 --save-dir ./ckpt --log-period 10

The config file is plain Python executed at startup. It must define:
    cost          — the v2/fluid cost variable (build the net at module top
                    level, exactly like a trainer_config_helpers config)
    optimizer     — a paddle_tpu.v2.optimizer.* (or fluid optimizer)
    train_reader  — callable yielding minibatches (lists of samples)
and may define:
    test_reader   — callable, evaluated at every pass end
    feeding       — {data_name: sample_index} feed-order map

The reference's --use_gpu / --trainer_count flags have no meaning here
(device selection is JAX's; parallelism is the mesh's) and are accepted
but ignored for config compatibility.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def _load_config(path: str) -> dict:
    cfg = runpy.run_path(path)
    missing = [k for k in ("cost", "optimizer", "train_reader")
               if k not in cfg]
    if missing:
        raise SystemExit(
            f"config {path!r} must define {missing} "
            "(see paddle_tpu/v2/trainer_cli.py docstring)")
    return cfg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.v2.trainer_cli",
        description="paddle_trainer-style CLI over the v2 facade",
    )
    ap.add_argument("--config", required=True,
                    help="python file declaring cost/optimizer/train_reader")
    ap.add_argument("--num-passes", type=int, default=1)
    ap.add_argument("--save-dir", default=None,
                    help="write params_pass_<n>.tar checkpoints here")
    ap.add_argument("--log-period", type=int, default=20,
                    help="print train cost every N batches")
    # accepted-but-ignored legacy flags (device/threading is JAX's job)
    ap.add_argument("--use_gpu", "--use-gpu", default=None, nargs="?")
    ap.add_argument("--trainer_count", "--trainer-count", default=None,
                    nargs="?")
    args = ap.parse_args(argv)

    from .. import v2 as paddle_v2
    from . import event as v2_event

    cfg = _load_config(args.config)
    cost, optimizer = cfg["cost"], cfg["optimizer"]
    parameters = paddle_v2.create(cost)
    trainer = paddle_v2.SGD(cost=cost, parameters=parameters,
                            update_equation=optimizer)

    test_reader = cfg.get("test_reader")
    feeding = cfg.get("feeding")
    if args.save_dir:
        os.makedirs(args.save_dir, exist_ok=True)

    def handler(e):
        if isinstance(e, v2_event.EndIteration):
            # log-period 0 = per-batch logging disabled
            if args.log_period > 0 and e.batch_id % args.log_period == 0:
                print(f"pass {e.pass_id} batch {e.batch_id} "
                      f"cost {e.cost:.6f}", flush=True)
        elif isinstance(e, v2_event.EndPass):
            if test_reader is not None:
                r = trainer.test(reader=test_reader, feeding=feeding)
                print(f"pass {e.pass_id} test cost {r.cost:.6f}", flush=True)
            if args.save_dir:
                p = os.path.join(args.save_dir,
                                 f"params_pass_{e.pass_id}.tar")
                with open(p, "wb") as f:
                    parameters.to_tar(f)
                print(f"saved {p}", flush=True)

    trainer.train(reader=cfg["train_reader"],
                  num_passes=args.num_passes,
                  event_handler=handler,
                  feeding=feeding)
    return 0


if __name__ == "__main__":
    sys.exit(main())
