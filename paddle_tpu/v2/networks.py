"""v2 network compositions (reference python/paddle/v2/networks.py →
trainer_config_helpers/networks.py): the multi-layer building blocks the
legacy DSL shipped — conv groups, bidirectional RNNs, text conv-pool,
whole-model VGG, and the seq2seq attention step — composed from the v2
layer functions / fluid layers."""
from __future__ import annotations

from ..fluid import layers as _fl
from ..fluid import nets as _nets
from . import layer as _v2


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    """reference networks.simple_img_conv_pool."""
    return _nets.simple_img_conv_pool(
        input=input, filter_size=filter_size, num_filters=num_filters,
        pool_size=pool_size, pool_stride=pool_stride,
        act=_v2._act_name(act),
    )


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, pool_stride=1,
                   pool_type="max", **kwargs):
    """reference networks.img_conv_group: N convs (+optional BN) then one
    pool — the VGG building block."""
    return _nets.img_conv_group(
        input=input, conv_num_filter=conv_num_filter, pool_size=pool_size,
        conv_padding=conv_padding, conv_filter_size=conv_filter_size,
        conv_act=_v2._act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm,
        pool_stride=pool_stride, pool_type=pool_type,
    )


def sequence_conv_pool(input, context_len, hidden_size, pool_type="max",
                       **kwargs):
    """reference networks.sequence_conv_pool / text_conv_pool."""
    return _nets.sequence_conv_pool(
        input=input, num_filters=hidden_size, filter_size=context_len,
        pool_type=pool_type,
    )


text_conv_pool = sequence_conv_pool


def lstmemory_group(input, size=None, reverse=False, param_attr=None,
                    lstm_bias_attr=None, input_proj_bias_attr=None,
                    act=None, **kwargs):
    """reference networks.lstmemory_group: the step-level LSTM — input
    already carries the 4*size projected gates (like lstmemory), but the
    recurrence is an explicit recurrent_group so other step-local layers
    can attach. param_attr names the recurrent weight; a shared name
    shares it across groups (tests/configs/shared_lstm.py)."""
    width = int(input.shape[-1])
    size = size or width // 4
    if width != size * 4:
        raise ValueError(
            f"lstmemory_group(size={size}) needs an input of width "
            f"{size * 4} (4*size projected gates), got {width}")

    def step(x_t):
        h_prev = _v2.memory(size=size)
        c_prev = _v2.memory(size=size)
        rec = _fl.fc(input=h_prev, size=size * 4, act=None,
                     param_attr=param_attr, bias_attr=lstm_bias_attr)
        gates = _fl.elementwise_add(x_t, rec)
        h, c = _v2.lstm_step_layer(gates, c_prev, size=size)
        return h, c

    outs = _v2.recurrent_group(step=step, input=input, reverse=reverse)
    return outs[0] if isinstance(outs, (list, tuple)) else outs


def gru_group(input, size=None, reverse=False, param_attr=None,
              gru_bias_attr=None, act=None, **kwargs):
    """reference networks.gru_group: step-level GRU over 3*size projected
    gates (the recurrent_group form of grumemory)."""
    width = int(input.shape[-1])
    size = size or width // 3
    if width != size * 3:
        raise ValueError(
            f"gru_group(size={size}) needs an input of width {size * 3} "
            f"(3*size projected gates), got {width}")

    def step(x_t):
        h_prev = _v2.memory(size=size)
        return _v2.gru_step_layer(x_t, h_prev, size=size)

    return _v2.recurrent_group(step=step, input=input, reverse=reverse)


def simple_lstm(input, size, reverse=False, **kwargs):
    """reference networks.simple_lstm: fc gate projection + lstmemory."""
    return _v2.simple_lstm(input, size, reverse=reverse)


def simple_gru(input, size, reverse=False, **kwargs):
    return _v2.simple_gru(input, size, reverse=reverse)


def bidirectional_lstm(input, size, return_seq=False, **kwargs):
    """reference networks.bidirectional_lstm: forward + backward lstm,
    concat (last states when return_seq=False, per-step otherwise)."""
    fwd = _v2.simple_lstm(input, size)
    bwd = _v2.simple_lstm(input, size, reverse=True)
    if return_seq:
        from ..fluid.layers import tensor as _t

        return _t.concat([fwd, bwd], axis=-1)
    from ..fluid.layers import tensor as _t

    # the reversed RNN's whole-sequence summary sits at the FIRST timestep
    # (the fused ops flip outputs back to original time order) — reference
    # networks.bidirectional_lstm: last_seq(fwd) + first_seq(bwd)
    return _t.concat(
        [_fl.sequence_last_step(fwd), _fl.sequence_first_step(bwd)], axis=-1)


def bidirectional_gru(input, size, return_seq=False, **kwargs):
    fwd = _v2.simple_gru(input, size)
    bwd = _v2.simple_gru(input, size, reverse=True)
    from ..fluid.layers import tensor as _t

    if return_seq:
        return _t.concat([fwd, bwd], axis=-1)
    return _t.concat(
        [_fl.sequence_last_step(fwd), _fl.sequence_first_step(bwd)], axis=-1)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     **kwargs):
    """reference networks.simple_attention (Bahdanau): score each encoder
    step against the decoder state, softmax over time, weighted sum."""
    size = int(encoded_proj.shape[-1])
    dec = _fl.fc(input=decoder_state, size=size, act=None)
    dec_expanded = _fl.sequence_expand(dec, encoded_proj)
    mix = _fl.tanh(_fl.elementwise_add(encoded_proj, dec_expanded))
    scores = _fl.fc(input=mix, size=1, num_flatten_dims=2, act=None)
    weights = _fl.sequence_softmax(scores)
    scaled = _fl.elementwise_mul(encoded_sequence, weights)
    return _fl.sequence_pool(scaled, "sum")


def vgg_16_network(input_image, num_channels, num_classes=1000, **kwargs):
    """reference networks.vgg_16_network: the canonical 5-group VGG-16."""
    del num_channels  # carried by the input's shape
    tmp = input_image
    for filters, n_convs in ((64, 2), (128, 2), (256, 3), (512, 3),
                             (512, 3)):
        tmp = img_conv_group(
            tmp, conv_num_filter=[filters] * n_convs, pool_size=2,
            conv_filter_size=3, conv_act="relu", pool_stride=2,
        )
    tmp = _fl.fc(input=tmp, size=4096, act="relu")
    tmp = _fl.dropout(tmp, dropout_prob=0.5)
    tmp = _fl.fc(input=tmp, size=4096, act="relu")
    tmp = _fl.dropout(tmp, dropout_prob=0.5)
    return _fl.fc(input=tmp, size=num_classes, act="softmax")
