"""v2 network compositions (reference python/paddle/v2/networks.py →
trainer_config_helpers/networks.py) mapped to fluid.nets."""
from __future__ import annotations

from ..fluid import nets as _nets


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    return _nets.simple_img_conv_pool(
        input=input, filter_size=filter_size, num_filters=num_filters,
        pool_size=pool_size, pool_stride=pool_stride, act=act,
    )


def sequence_conv_pool(input, context_len, hidden_size, **kwargs):
    return _nets.sequence_conv_pool(
        input=input, num_filters=hidden_size, filter_size=context_len,
    )
