"""reference python/paddle/v2/evaluator.py: evaluator facade — the v2
names map onto the fluid metrics/evaluator stack."""
from ..fluid.evaluator import Accuracy, ChunkEvaluator, EditDistance  # noqa: F401
from ..fluid.layers.nn import accuracy  # noqa: F401

classification_error = accuracy
