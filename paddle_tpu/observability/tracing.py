"""Thread-safe trace recorder: named host spans into a bounded ring
buffer, exported as chrome://tracing JSON (the role the reference's
device_tracer.cc + tools/timeline.py played — see ISSUE 1).

Design constraints:
  - Near-zero cost when disabled: `span()` checks one module-level bool
    and returns a shared no-op context manager; no allocation, no clock
    read, no lock.
  - Thread-safe when enabled: each completed span appends ONE tuple to a
    `collections.deque(maxlen=...)` — an atomic operation under the GIL,
    so concurrent executor / RPC handler / reader worker threads never
    contend on a lock in the hot path. Overflow drops the OLDEST spans
    (ring-buffer semantics) and counts the drops.
  - Complete events ("ph": "X"): one record per finished span carrying
    ts + dur. Chrome/Perfetto reconstruct nesting per (pid, tid) from
    the intervals, so cross-thread nesting needs no begin/end pairing.

Control surface: FLAGS["trace"] / FLAGS["trace_buffer"] (env
PADDLE_TPU_TRACE / PADDLE_TPU_TRACE_BUFFER) seed the initial state;
`trace_enable()` / `trace_disable()` toggle at runtime (fluid.profiler
drives these so the legacy profiler() API records traces too).
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "span", "trace_enable", "trace_disable", "trace_enabled",
    "trace_reset", "trace_export", "trace_events", "dropped_spans",
    "resize_buffer", "buffer_capacity",
]

# epoch for ts fields: chrome trace wants monotonically comparable
# microseconds; perf_counter is monotonic and high-resolution
_EPOCH = time.perf_counter()

_enabled = False
_buf: "collections.deque" = collections.deque(maxlen=65536)
_dropped = 0
_mu = threading.Lock()  # guards enable/reset/export, NOT the append path


def _env_flag(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).lower() in ("1", "true", "yes", "on")


def _configure_from_env():
    global _enabled, _buf
    cap = int(os.environ.get("PADDLE_TPU_TRACE_BUFFER", "65536") or 65536)
    _buf = collections.deque(maxlen=max(16, cap))
    _enabled = _env_flag("PADDLE_TPU_TRACE")


_configure_from_env()


class _NullSpan:
    """Shared no-op context for the disabled path: __enter__/__exit__ do
    nothing, `set_arg` swallows; one instance serves every call site."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_arg(self, key, value):
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """RAII host span. Records a complete event at __exit__ — begin time,
    duration, thread id, and optional args — into the ring buffer."""

    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        global _dropped
        t1 = time.perf_counter()
        if len(_buf) == _buf.maxlen:
            _dropped += 1  # GIL-atomic enough for a diagnostics counter
        _buf.append((
            self.name,
            (self._t0 - _EPOCH) * 1e6,      # ts, µs
            (t1 - self._t0) * 1e6,          # dur, µs
            threading.get_ident(),
            self.args,
        ))
        return False

    def set_arg(self, key, value):
        if self.args is None:
            self.args = {}
        self.args[key] = value


def span(name: str, **args):
    """`with span("executor.step", step=3): ...` — the one tracing entry
    point every instrumented layer uses. Disabled: one bool check, a
    shared no-op object, and (unavoidably) the kwargs dict the caller
    built; hot paths that can't afford even that should guard with
    `if trace_enabled():`."""
    if not _enabled:
        return _NULL_SPAN
    return Span(name, args or None)


def trace_enabled() -> bool:
    return _enabled


def trace_enable(buffer_size: Optional[int] = None):
    global _enabled
    with _mu:
        if buffer_size is not None:
            _resize_locked(buffer_size)
        _enabled = True


def trace_disable():
    global _enabled
    with _mu:
        _enabled = False


def _resize_locked(capacity: int):
    global _buf
    if capacity != _buf.maxlen:
        _buf = collections.deque(_buf, maxlen=max(16, int(capacity)))


def resize_buffer(capacity: int):
    """Change ring capacity, keeping buffered spans (newest win) and the
    current enable state."""
    with _mu:
        _resize_locked(capacity)


def buffer_capacity() -> int:
    return _buf.maxlen or 0


def trace_reset():
    global _dropped
    with _mu:
        _buf.clear()
        _dropped = 0


def dropped_spans() -> int:
    return _dropped


def trace_events() -> List[Dict[str, Any]]:
    """The buffered spans as chrome trace event dicts (oldest first)."""
    pid = os.getpid()
    out = []
    for name, ts, dur, tid, args in list(_buf):
        ev = {"name": name, "ph": "X", "ts": ts, "dur": dur,
              "pid": pid, "tid": tid, "cat": "host"}
        if args:
            ev["args"] = args
        out.append(ev)
    return out


def trace_export(path: str) -> str:
    """Write the buffer as a chrome://tracing / Perfetto-loadable JSON
    object. `path` may be a directory (the legacy profiler profile_path
    contract allowed one); then the file is <path>/trace.json. Returns
    the path actually written."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    doc = {
        "traceEvents": trace_events(),
        "displayTimeUnit": "ms",
        "otherData": {"dropped_spans": _dropped},
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
