"""Thread-safe trace recorder: named host spans into a bounded ring
buffer, exported as chrome://tracing JSON (the role the reference's
device_tracer.cc + tools/timeline.py played — see ISSUE 1), now with
CROSS-PROCESS trace context (ISSUE 3): every span carries a trace_id /
span_id / parent_id, a remote peer can adopt a context received on the
wire (distributed/rpc.py stamps a `__trace__` header into every frame),
and chrome FLOW events ("ph": "s"/"f") link a client RPC span to its
server handler span so Perfetto draws the client→server arrow across
process boundaries.

Design constraints:
  - Near-zero cost when disabled: `span()` checks one module-level bool
    and returns a shared no-op context manager; no allocation, no clock
    read, no lock, no id minting.
  - Thread-safe when enabled: each completed span appends ONE tuple to a
    `collections.deque(maxlen=...)` — an atomic operation under the GIL,
    so concurrent executor / RPC handler / reader worker threads never
    contend on a lock in the hot path. Overflow drops the OLDEST spans
    (ring-buffer semantics) and counts the drops (also exported as the
    `tracing.dropped_spans` gauge so span loss is visible in /metrics).
  - Complete events ("ph": "X"): one record per finished span carrying
    ts + dur. Chrome/Perfetto reconstruct nesting per (pid, tid) from
    the intervals, so cross-thread nesting needs no begin/end pairing.
  - Trace context rides a per-thread stack: a span's parent is the
    innermost open span on its thread, or — for the outermost span of an
    RPC handler — the remote context adopted from the frame header.

Cross-process clock alignment: `ts` is process-local (perf_counter from
a per-process epoch), so shards from different processes are not
directly comparable. Each export records `wall_epoch_us` (the wall-clock
time of the process's trace epoch) plus `rpc_clock_offset_us` (an
NTP-style offset estimate the RPC layer feeds from request/response
timestamps — note_clock_offset). `timeline merge` uses both to place
every shard on one axis.

Control surface: FLAGS["trace"] / FLAGS["trace_buffer"] (env
PADDLE_TPU_TRACE / PADDLE_TPU_TRACE_BUFFER) seed the initial state;
`trace_enable()` / `trace_disable()` toggle at runtime (fluid.profiler
drives these so the legacy profiler() API records traces too). With
PADDLE_TPU_TRACE_DIR set, an atexit hook exports this process's shard
to `<dir>/trace-<pid>.json` — how multi-process jobs (and
tools/chaos_soak.py --trace-dir) collect per-process shards without
any code in the trainer.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

__all__ = [
    "span", "trace_enable", "trace_disable", "trace_enabled",
    "trace_reset", "trace_export", "trace_events", "dropped_spans",
    "resize_buffer", "buffer_capacity",
    "current_span", "wire_context", "adopt", "flow_start", "flow_end",
    "new_flow_id",
    "set_process_label", "process_label", "note_clock_offset",
    "clock_offset_us", "wall_epoch_us", "shard_path",
]

# epoch for ts fields: chrome trace wants monotonically comparable
# microseconds; perf_counter is monotonic and high-resolution.
# _WALL_EPOCH_US anchors that epoch to the wall clock (captured at the
# same instant) so `timeline merge` can align shards from different
# processes on one axis.
_EPOCH = time.perf_counter()
_WALL_EPOCH_US = time.time() * 1e6

_enabled = False
# _mu guards the ring's REBINDS and clears (enable/resize/reset); the
# append/snapshot path is deliberately lock-free — deque ops are
# GIL-atomic — and carries per-site allow-unguarded vets
_buf: "collections.deque" = collections.deque(maxlen=65536)  # guarded-by: _mu
_dropped = 0
_mu = threading.Lock()

# trace identity: ids are "<proc>-<n>" — unique across processes (the
# proc component is a per-process uuid) and cheap to mint (one counter
# increment, GIL-atomic via itertools.count)
_PROC = uuid.uuid4().hex[:12]
_ids = itertools.count(1)

# per-thread context: .span = innermost open Span, .remote = adopted
# (trace_id, parent_span_id) from a wire header (RPC handler threads)
_tls = threading.local()

# process label for the merged timeline ("pserver:7001", "trainer:0");
# param_server/master/elastic set it when they start serving
_process_label: Optional[str] = None

# EWMA of this process's clock offset relative to the RPC peers it
# calls (peer_wall - local_wall, µs) — fed by note_clock_offset from
# the client's request/response timestamp handshake
_clock_offset = None  # type: Optional[float]

# span loss exported as a gauge (ISSUE 3 satellite): registered EAGERLY
# so /metrics always shows the line — a scrape must distinguish "zero
# drops" from "nobody measured". metrics has no import back-edge to
# tracing, so this is cycle-free.
from . import metrics as _metrics  # noqa: E402

_g_dropped = _metrics.gauge("tracing.dropped_spans")


def _env_flag(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).lower() in ("1", "true", "yes", "on")


def _configure_from_env():
    global _enabled, _buf
    cap = int(os.environ.get("PADDLE_TPU_TRACE_BUFFER", "65536") or 65536)
    _buf = collections.deque(maxlen=max(16, cap))
    _enabled = _env_flag("PADDLE_TPU_TRACE")
    if os.environ.get("PADDLE_TPU_TRACE_DIR"):
        import atexit

        atexit.register(_export_shard_at_exit)
        # atexit never fires on SIGTERM — and SIGTERM is how real jobs
        # stop a pserver, which would silently lose exactly the shard an
        # operator set PADDLE_TPU_TRACE_DIR to collect. The env flag is
        # an explicit opt-in, so chaining a TERM handler here is the
        # operator's intent, not a library land-grab; any pre-installed
        # handler still runs after the export.
        _install_sigterm_export()


def _install_sigterm_export():
    import signal

    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            _export_shard_at_exit()
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_IGN:
                return  # the process chose to survive TERM: keep that
            else:  # SIG_DFL (or an unknown non-Python handler): die as
                # the process would have without us
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread (embedded import): atexit still covers
        # normal exits; SIGTERM loss is unavoidable there


def _export_shard_at_exit():  # lint: allow-unguarded(_buf) — atexit read;
    # a non-empty check on a GIL-atomic deque needs no lock
    d = os.environ.get("PADDLE_TPU_TRACE_DIR")
    if d and _buf:
        try:
            trace_export(shard_path(d))
        except OSError:
            pass  # a dying process must not mask its real exit cause


def shard_path(trace_dir: str) -> str:
    """The per-process shard file `timeline merge` expects."""
    return os.path.join(trace_dir, f"trace-{os.getpid()}.json")


_configure_from_env()


def _new_id() -> str:
    return f"{_PROC}-{next(_ids)}"


def _note_drop():
    """Count a ring-buffer overflow and mirror it into the
    tracing.dropped_spans gauge (satellite: span loss must be visible in
    /metrics, not only in the export's otherData)."""
    global _dropped
    _dropped += 1
    _g_dropped.set(_dropped)


class _NullSpan:
    """Shared no-op context for the disabled path: __enter__/__exit__ do
    nothing, `set_arg` swallows; one instance serves every call site."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_arg(self, key, value):
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """RAII host span. Records a complete event at __exit__ — begin time,
    duration, thread id, trace context, and optional args — into the ring
    buffer. While open it is its thread's current span: child spans (and
    wire_context()) read their parent from it."""

    __slots__ = ("name", "args", "_t0", "_prev",
                 "trace_id", "span_id", "parent_id")

    def __init__(self, name: str, args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.args = args
        self._t0 = 0.0
        self._prev = None
        self.trace_id = self.span_id = self.parent_id = None

    def __enter__(self):
        parent = getattr(_tls, "span", None)
        self._prev = parent
        if parent is not None:
            self.trace_id, self.parent_id = parent.trace_id, parent.span_id
        else:
            remote = getattr(_tls, "remote", None)
            if remote is not None:
                self.trace_id, self.parent_id = remote
            else:
                self.trace_id = _new_id()  # root span starts a new trace
        self.span_id = _new_id()
        _tls.span = self
        self._t0 = time.perf_counter()
        return self

    # lint: allow-unguarded(_buf) — THE hot append path: one deque.append
    # per finished span, GIL-atomic by design (see module docstring); _mu
    # here would serialize every instrumented thread on every span
    def __exit__(self, *exc):
        t1 = time.perf_counter()
        _tls.span = self._prev
        if len(_buf) == _buf.maxlen:
            _note_drop()
        _buf.append((
            self.name,
            (self._t0 - _EPOCH) * 1e6,      # ts, µs
            (t1 - self._t0) * 1e6,          # dur, µs
            threading.get_ident(),
            self.args,
            (self.trace_id, self.span_id, self.parent_id),
        ))
        return False

    def set_arg(self, key, value):
        if self.args is None:
            self.args = {}
        self.args[key] = value


def span(name: str, **args):
    """`with span("executor.step", step=3): ...` — the one tracing entry
    point every instrumented layer uses. Disabled: one bool check, a
    shared no-op object, and (unavoidably) the kwargs dict the caller
    built; hot paths that can't afford even that should guard with
    `if trace_enabled():`."""
    if not _enabled:
        return _NULL_SPAN
    return Span(name, args or None)


def current_span() -> Optional[Span]:
    """The innermost open span on this thread, or None."""
    return getattr(_tls, "span", None)


def new_flow_id() -> str:
    """A flow-event id unique ACROSS processes (proc-uuid prefixed) —
    callers without a natural per-call token (the RPC layer reuses its
    idempotency token) mint one here."""
    return _new_id()


def wire_context(flow_id: Optional[str] = None) -> Optional[dict]:
    """The `__trace__` header an RPC client stamps into a frame: the
    current span's trace_id ("t") and span_id ("s" — the server span's
    remote parent), plus the flow-event id ("f") linking the two sides.
    None when tracing is off or no span is open (frames stay clean)."""
    if not _enabled:
        return None
    sp = getattr(_tls, "span", None)
    if sp is None:
        return None
    ctx = {"t": sp.trace_id, "s": sp.span_id}
    if flow_id is not None:
        ctx["f"] = str(flow_id)
    return ctx


class _Adopt:
    """Context manager installing a remote (trace_id, parent_span_id) as
    this thread's root context: the next span opened with NO local parent
    inherits it — how an RPC handler's span joins the client's trace."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "remote", None)
        _tls.remote = self._ctx
        return self

    def __exit__(self, *exc):
        _tls.remote = self._prev
        return False


_NULL_ADOPT = _Adopt(None)


def adopt(wire: Optional[dict]):
    """`with adopt(req.pop("__trace__", None)), span("rpc.server.x"): ...`
    — server-side half of context propagation. A None/foreign header (or
    tracing disabled) is a no-op."""
    if not _enabled or not isinstance(wire, dict) or "t" not in wire:
        return _NULL_ADOPT
    return _Adopt((wire.get("t"), wire.get("s")))


def flow_start(flow_id):  # lint: allow-unguarded(_buf) — lock-free append
    # path, same GIL-atomicity vet as Span.__exit__
    """Record a chrome flow-START event at now; chrome binds it to the
    enclosing slice on this (pid, tid) — call inside the client span."""
    if not _enabled or flow_id is None:
        return
    if len(_buf) == _buf.maxlen:
        _note_drop()
    _buf.append(("s", (time.perf_counter() - _EPOCH) * 1e6,
                 threading.get_ident(), str(flow_id)))


def flow_end(flow_id):  # lint: allow-unguarded(_buf) — lock-free append
    # path, same GIL-atomicity vet as Span.__exit__
    """The matching flow-FINISH — call inside the server handler span."""
    if not _enabled or flow_id is None:
        return
    if len(_buf) == _buf.maxlen:
        _note_drop()
    _buf.append(("f", (time.perf_counter() - _EPOCH) * 1e6,
                 threading.get_ident(), str(flow_id)))


def set_process_label(label: str):
    """Name this process in merged timelines ("pserver:7001"); emitted as
    a process_name metadata event on export. Last writer wins."""
    global _process_label
    _process_label = str(label)


def process_label() -> Optional[str]:
    return _process_label


def note_clock_offset(offset_us: float):
    """Feed one NTP-style offset sample (server_wall - client_wall
    midpoint, µs) from an RPC handshake; an EWMA smooths jitter. The
    export records the estimate for `timeline merge` clock alignment."""
    global _clock_offset
    offset_us = float(offset_us)
    _clock_offset = (offset_us if _clock_offset is None
                     else 0.8 * _clock_offset + 0.2 * offset_us)


def clock_offset_us() -> Optional[float]:
    return _clock_offset


def wall_epoch_us() -> float:
    """Wall-clock µs of this process's trace epoch (ts=0)."""
    return _WALL_EPOCH_US


def trace_enabled() -> bool:
    return _enabled


def trace_enable(buffer_size: Optional[int] = None):
    global _enabled
    with _mu:
        if buffer_size is not None:
            _resize_locked(buffer_size)
        _enabled = True


def trace_disable():
    global _enabled
    with _mu:
        _enabled = False


def _resize_locked(capacity: int):
    global _buf
    if capacity != _buf.maxlen:
        _buf = collections.deque(_buf, maxlen=max(16, int(capacity)))


def resize_buffer(capacity: int):
    """Change ring capacity, keeping buffered spans (newest win) and the
    current enable state."""
    with _mu:
        _resize_locked(capacity)


def buffer_capacity() -> int:  # lint: allow-unguarded(_buf) — one atomic
    # attribute read of an immutable deque property
    return _buf.maxlen or 0


def trace_reset():
    global _dropped
    with _mu:
        _buf.clear()
        _dropped = 0
        if _g_dropped is not None:
            _g_dropped.set(0)


def dropped_spans() -> int:
    return _dropped


def trace_events() -> List[Dict[str, Any]]:  # lint: allow-unguarded(_buf)
    # — list(deque) is one GIL-atomic snapshot; concurrent appends land
    # before or after it, never mid-copy
    """The buffered records as chrome trace event dicts (oldest first):
    complete ("X") span events — trace context in args — plus flow
    start/finish ("s"/"f") events."""
    pid = os.getpid()
    out = []
    for rec in list(_buf):
        if len(rec) == 4:  # flow record — spans are 6-tuples (a span
            # literally NAMED "s"/"f" must not take this branch)
            ph, ts, tid, fid = rec
            ev = {"name": "rpc", "cat": "rpc", "ph": ph, "id": fid,
                  "ts": ts, "pid": pid, "tid": tid}
            if ph == "f":
                ev["bp"] = "e"  # bind to the ENCLOSING slice, not the next
            out.append(ev)
            continue
        name, ts, dur, tid, args, trace = rec
        ev = {"name": name, "ph": "X", "ts": ts, "dur": dur,
              "pid": pid, "tid": tid, "cat": "host"}
        ev_args = dict(args) if args else {}
        if trace is not None and trace[0] is not None:
            ev_args["trace_id"] = trace[0]
            ev_args["span_id"] = trace[1]
            if trace[2] is not None:
                ev_args["parent_span_id"] = trace[2]
        if ev_args:
            ev["args"] = ev_args
        out.append(ev)
    return out


def trace_export(path: str) -> str:
    """Write the buffer as a chrome://tracing / Perfetto-loadable JSON
    object. `path` may be a directory (the legacy profiler profile_path
    contract allowed one); then the file is <path>/trace.json. Returns
    the path actually written.

    otherData carries what `timeline merge` needs to align this shard
    with shards from other processes: pid, process_label, wall_epoch_us
    (wall time of ts=0) and rpc_clock_offset_us (EWMA skew vs RPC
    peers). A process_name metadata event names the track in Perfetto.
    """
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    pid = os.getpid()
    label = _process_label or f"python:{pid}"
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "args": {"name": label}}]
    events += trace_events()
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "dropped_spans": _dropped,
            "pid": pid,
            "process_label": label,
            "wall_epoch_us": _WALL_EPOCH_US,
            "rpc_clock_offset_us": _clock_offset or 0.0,
        },
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
