"""Timeline CLI — the tools/timeline.py role for exported traces.

  python -m paddle_tpu.observability.timeline trace.json [--top N]
      print a per-span-name summary (calls, total/avg/max ms, % of
      wall) of a chrome://tracing JSON file, heaviest first.

  python -m paddle_tpu.observability.timeline merge -o merged.json \
      shard1.json shard2.json ...
      combine per-process trace shards (ISSUE 3: each process exports
      its own ring buffer — see PADDLE_TPU_TRACE_DIR) into ONE
      Perfetto-loadable timeline. Every shard's process-local timestamps
      are rebased onto a shared axis using the shard's wall-clock epoch
      anchor plus its RPC-handshake clock-offset estimate (both recorded
      in otherData by trace_export), so a client span and its server
      handler span line up even across skewed clocks. Flow events and
      trace ids pass through untouched — Perfetto draws the
      client→server arrows.

  python -m paddle_tpu.observability.timeline --selftest
      record a synthetic multi-thread trace through the real recorder,
      export it, and validate the JSON round-trips with well-formed
      ph/ts/dur fields and correct cross-thread nesting; then exercise
      merge on overlapping, clock-skewed shards and the missing-shard
      error path. Exit 0 on success — tier-1 runs this so a broken
      exporter (or merger) fails fast.

Traces open in Perfetto (https://ui.perfetto.dev) or chrome://tracing.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List


def load_events(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError(f"{path}: no traceEvents list")
    return events


def summarize(events: List[Dict[str, Any]], top: int = 20) -> str:
    """Top-N table by total duration. Only complete ("X") events carry
    dur; B/E pairs from foreign tools are ignored rather than guessed."""
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # calls, total_us, max_us
    t_min, t_max = float("inf"), float("-inf")
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        ts = float(ev.get("ts", 0.0))
        rec = agg[ev.get("name", "?")]
        rec[0] += 1
        rec[1] += dur
        rec[2] = max(rec[2], dur)
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
    wall_us = (t_max - t_min) if t_max > t_min else 0.0
    rows = sorted(agg.items(), key=lambda kv: kv[1][1], reverse=True)[:top]
    lines = [
        f"{'Span':<44}{'Calls':>7}{'Total(ms)':>11}{'Avg(ms)':>10}"
        f"{'Max(ms)':>10}{'%Wall':>8}"
    ]
    for name, (calls, total, mx) in rows:
        pct = (total / wall_us * 100.0) if wall_us else 0.0
        lines.append(
            f"{name[:44]:<44}{calls:>7}{total / 1e3:>11.3f}"
            f"{total / calls / 1e3:>10.3f}{mx / 1e3:>10.3f}{pct:>7.1f}%"
        )
    lines.append(
        f"-- {sum(r[0] for r in agg.values())} spans, "
        f"{len(agg)} distinct names, wall {wall_us / 1e3:.3f} ms"
    )
    return "\n".join(lines)


# --- multi-process merge -------------------------------------------------


def merge_shards(paths: List[str]) -> Dict[str, Any]:
    """Combine per-process shards into one timeline document.

    Alignment: a shard's ts values are µs since ITS process's trace
    epoch. otherData.wall_epoch_us (wall time of that epoch) maps them
    onto the wall clock; otherData.rpc_clock_offset_us (the NTP-style
    estimate the RPC layer maintains: peer_wall - local_wall) corrects
    residual skew toward the servers the process talked to. Everything
    is then rebased to the earliest event so Perfetto opens at t=0.

    Raises FileNotFoundError/ValueError on a missing or malformed shard
    — a partial merge would silently present an incomplete job as the
    whole job.
    """
    if not paths:
        raise ValueError("merge needs at least one shard")
    shards = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except FileNotFoundError:
            raise FileNotFoundError(f"shard not found: {p}") from None
        except json.JSONDecodeError as e:
            raise ValueError(f"{p}: not valid trace JSON ({e})") from None
        if not isinstance(doc, dict) or \
                not isinstance(doc.get("traceEvents"), list):
            raise ValueError(f"{p}: no traceEvents list")
        other = doc.get("otherData") or {}
        shards.append({
            "path": p,
            "events": doc["traceEvents"],
            "wall_epoch_us": float(other.get("wall_epoch_us", 0.0)),
            "offset_us": float(other.get("rpc_clock_offset_us", 0.0)),
            "pid": other.get("pid"),
            "label": other.get("process_label"),
            "dropped": int(other.get("dropped_spans", 0)),
        })

    # two shards can share an OS pid (pid reuse across hosts/restarts);
    # remap collisions to synthetic pids so Perfetto keeps the process
    # tracks separate
    used_pids: set = set()
    for i, sh in enumerate(shards):
        pid = sh["pid"]
        ev_pids = {e.get("pid") for e in sh["events"]
                   if e.get("pid") is not None}
        if pid is None:
            pid = next(iter(ev_pids), 1000 + i)
        remap = pid in used_pids
        new_pid = pid
        while new_pid in used_pids:
            new_pid += 100000
        used_pids.add(new_pid)
        sh["out_pid"] = new_pid
        sh["remap_from"] = pid if remap else None

    # Rebase in SMALL numbers: wall anchors are ~1e15 µs, and adding a
    # shard-local ts (~1e3 µs) to them in float64 quantizes at ~0.25 µs —
    # subtracting two such sums can surface as a (tiny) negative ts.
    # Subtract the anchors from each other FIRST (one cancellation per
    # shard), then work in per-shard relative shifts.
    base = min(sh["wall_epoch_us"] + sh["offset_us"] for sh in shards)
    for sh in shards:
        sh["rel_us"] = (sh["wall_epoch_us"] + sh["offset_us"]) - base
    t_min = min((float(ev["ts"]) + sh["rel_us"]
                 for sh in shards for ev in sh["events"] if "ts" in ev),
                default=0.0)
    merged: List[Dict[str, Any]] = []
    for sh in shards:
        shift = sh["rel_us"] - t_min
        for ev in sh["events"]:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = max(0.0, float(ev["ts"]) + shift)
            if sh["out_pid"] is not None:
                ev["pid"] = sh["out_pid"]
            merged.append(ev)
        if not any(ev.get("ph") == "M" and ev.get("name") == "process_name"
                   for ev in sh["events"]):
            merged.append({"name": "process_name", "ph": "M",
                           "pid": sh["out_pid"],
                           "args": {"name": sh["label"]
                                    or f"pid {sh['pid']}"}})
    # Perfetto doesn't require order, but a sorted file diffs/tails sanely
    merged.sort(key=lambda e: e.get("ts", -1.0))
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_shards": [
                {"path": sh["path"], "pid": sh["pid"],
                 "process_label": sh["label"],
                 "clock_offset_us": sh["offset_us"],
                 "dropped_spans": sh["dropped"]}
                for sh in shards
            ],
            "dropped_spans": sum(sh["dropped"] for sh in shards),
        },
    }


def _merge_main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.timeline merge",
        description="Merge per-process trace shards into one "
                    "Perfetto-loadable timeline.")
    ap.add_argument("shards", nargs="+", help="per-process trace JSONs "
                    "(PADDLE_TPU_TRACE_DIR exports trace-<pid>.json)")
    ap.add_argument("-o", "--out", default="merged_trace.json",
                    help="output path (default merged_trace.json)")
    args = ap.parse_args(argv)
    try:
        doc = merge_shards(args.shards)
    except (FileNotFoundError, ValueError) as e:
        print(f"merge failed: {e}", file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        json.dump(doc, f)
    n_x = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    n_flow = sum(1 for e in doc["traceEvents"] if e.get("ph") in ("s", "f"))
    print(f"merged {len(args.shards)} shard(s) -> {args.out}: "
          f"{n_x} spans, {n_flow} flow events, "
          f"{doc['otherData']['dropped_spans']} dropped")
    print(summarize(doc["traceEvents"]))
    return 0


# --- selftest ------------------------------------------------------------


def _selftest() -> int:
    """End-to-end recorder -> exporter -> parser check on a synthetic
    workload with nested and cross-thread spans, then a merge check over
    overlapping clock-skewed shards and the missing-shard error path."""
    import os
    import tempfile
    import threading
    import time

    from . import tracing

    tracing.trace_enable(buffer_size=4096)
    tracing.trace_reset()
    try:
        with tracing.span("selftest.parent", step=1):
            with tracing.span("selftest.child"):
                time.sleep(0.002)
            with tracing.span("selftest.child"):
                time.sleep(0.001)

        def worker():
            with tracing.span("selftest.worker"):
                time.sleep(0.001)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        with tempfile.TemporaryDirectory() as d:
            path = tracing.trace_export(os.path.join(d, "trace.json"))
            events = load_events(path)
    finally:
        tracing.trace_disable()
        tracing.trace_reset()

    by_name = defaultdict(list)
    for ev in events:
        if ev.get("ph") == "M":
            continue  # process metadata carries no ts/dur
        for field in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert field in ev, f"event missing {field!r}: {ev}"
        assert ev["ph"] == "X", ev
        assert ev["dur"] >= 0 and ev["ts"] >= 0, ev
        by_name[ev["name"]].append(ev)
    assert len(by_name["selftest.parent"]) == 1, by_name
    assert len(by_name["selftest.child"]) == 2, by_name
    assert len(by_name["selftest.worker"]) == 1, by_name
    parent = by_name["selftest.parent"][0]
    assert parent["args"]["step"] == 1, parent
    # every span carries trace context; children inherit the parent's
    # trace_id, roots start their own
    assert "trace_id" in parent["args"] and "span_id" in parent["args"]
    p0, p1 = parent["ts"], parent["ts"] + parent["dur"]
    for child in by_name["selftest.child"]:
        assert p0 <= child["ts"] and child["ts"] + child["dur"] <= p1, \
            (parent, child)
        assert child["tid"] == parent["tid"]
        assert child["args"]["trace_id"] == parent["args"]["trace_id"]
        assert child["args"]["parent_span_id"] == parent["args"]["span_id"]
    assert by_name["selftest.worker"][0]["tid"] != parent["tid"]
    print(summarize(events))

    _selftest_merge()
    print("timeline selftest ok")
    return 0


def _selftest_merge():
    """Merge validation: two overlapping shards with deliberate clock
    skew must land on one corrected axis with both processes' spans,
    flow events intact; a missing shard must be a hard error."""
    import os
    import tempfile

    def shard(pid, label, wall_epoch_us, offset_us, events):
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"pid": pid, "process_label": label,
                              "wall_epoch_us": wall_epoch_us,
                              "rpc_clock_offset_us": offset_us,
                              "dropped_spans": 0}}

    # client's clock runs 500µs BEHIND the server's (its recorded wall
    # epoch is low by 500); the RPC handshake measured offset +500.
    # True client window: (1_000_000+500) + 100..500 = 1_000_600..1_001_000;
    # server handler (epoch 1_000_200, no skew) at 450..550 = 1_000_650..750
    # sits INSIDE it. Uncorrected, the handler would appear to start
    # AFTER the client call already returned — physically impossible.
    client = shard(11, "trainer:0", 1_000_000.0, 500.0, [
        {"name": "rpc.client.push_grad", "ph": "X", "ts": 100.0,
         "dur": 400.0, "pid": 11, "tid": 1,
         "args": {"trace_id": "T1", "span_id": "C1"}},
        {"name": "rpc", "cat": "rpc", "ph": "s", "id": "F1",
         "ts": 120.0, "pid": 11, "tid": 1},
    ])
    server = shard(22, "pserver:7000", 1_000_200.0, 0.0, [
        {"name": "rpc.server.push_grad", "ph": "X", "ts": 450.0,
         "dur": 100.0, "pid": 22, "tid": 9,
         "args": {"trace_id": "T1", "span_id": "S1",
                  "parent_span_id": "C1"}},
        {"name": "rpc", "cat": "rpc", "ph": "f", "bp": "e", "id": "F1",
         "ts": 455.0, "pid": 22, "tid": 9},
    ])
    # the BROKEN ordering the offset correction fixes: on raw wall
    # anchors alone the handler would start at 1_000_650 but the client
    # call would END at 1_000_500 — assert the skew scenario is real
    assert (1_000_200.0 + 450.0) > (1_000_000.0 + 500.0)
    with tempfile.TemporaryDirectory() as d:
        pa = os.path.join(d, "a.json")
        pb = os.path.join(d, "b.json")
        with open(pa, "w") as f:
            json.dump(client, f)
        with open(pb, "w") as f:
            json.dump(server, f)
        doc = merge_shards([pa, pb])
        evs = doc["traceEvents"]
        cl = next(e for e in evs if e["name"] == "rpc.client.push_grad")
        sv = next(e for e in evs if e["name"] == "rpc.server.push_grad")
        # shared trace id + parentage survived the merge
        assert sv["args"]["trace_id"] == cl["args"]["trace_id"]
        assert sv["args"]["parent_span_id"] == cl["args"]["span_id"]
        # corrected axis: the server handler runs INSIDE the client call
        # window (client 100..500 + offset 500 -> wall 1_000_600..
        # 1_001_000; server 250..350 -> wall 1_000_650..750)
        assert cl["ts"] <= sv["ts"], (cl["ts"], sv["ts"])
        assert sv["ts"] + sv["dur"] <= cl["ts"] + cl["dur"]
        # flow pair intact, ids matching, start before finish
        fs = next(e for e in evs if e.get("ph") == "s")
        fe = next(e for e in evs if e.get("ph") == "f")
        assert fs["id"] == fe["id"] == "F1"
        assert fs["ts"] <= fe["ts"]
        # both processes present, distinctly named
        names = {e["args"]["name"] for e in evs
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert names == {"trainer:0", "pserver:7000"}, names
        # missing shard: loud failure, not a partial merge
        try:
            merge_shards([pa, os.path.join(d, "nope.json")])
        except FileNotFoundError:
            pass
        else:
            raise AssertionError("missing shard did not raise")
    print("merge selftest ok")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "merge":
        return _merge_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.timeline",
        description="Summarize a chrome://tracing JSON exported by "
                    "paddle_tpu (trace_export / profiler profile_path), "
                    "or `merge` per-process shards into one timeline.")
    ap.add_argument("trace", nargs="?", help="path to trace JSON")
    ap.add_argument("--top", type=int, default=20,
                    help="rows in the summary table (default 20)")
    ap.add_argument("--selftest", action="store_true",
                    help="validate the recorder/exporter/merger round trip")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.trace:
        ap.error("need a trace file (or `merge`, or --selftest)")
    print(summarize(load_events(args.trace), top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
