"""Timeline CLI — the tools/timeline.py role for exported traces.

  python -m paddle_tpu.observability.timeline trace.json [--top N]
      print a per-span-name summary (calls, total/avg/max ms, % of
      wall) of a chrome://tracing JSON file, heaviest first.

  python -m paddle_tpu.observability.timeline --selftest
      record a synthetic multi-thread trace through the real recorder,
      export it, and validate the JSON round-trips with well-formed
      ph/ts/dur fields and correct cross-thread nesting. Exit 0 on
      success — tier-1 runs this so a broken exporter fails fast.

Traces open in Perfetto (https://ui.perfetto.dev) or chrome://tracing.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List


def load_events(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError(f"{path}: no traceEvents list")
    return events


def summarize(events: List[Dict[str, Any]], top: int = 20) -> str:
    """Top-N table by total duration. Only complete ("X") events carry
    dur; B/E pairs from foreign tools are ignored rather than guessed."""
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # calls, total_us, max_us
    t_min, t_max = float("inf"), float("-inf")
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        ts = float(ev.get("ts", 0.0))
        rec = agg[ev.get("name", "?")]
        rec[0] += 1
        rec[1] += dur
        rec[2] = max(rec[2], dur)
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
    wall_us = (t_max - t_min) if t_max > t_min else 0.0
    rows = sorted(agg.items(), key=lambda kv: kv[1][1], reverse=True)[:top]
    lines = [
        f"{'Span':<44}{'Calls':>7}{'Total(ms)':>11}{'Avg(ms)':>10}"
        f"{'Max(ms)':>10}{'%Wall':>8}"
    ]
    for name, (calls, total, mx) in rows:
        pct = (total / wall_us * 100.0) if wall_us else 0.0
        lines.append(
            f"{name[:44]:<44}{calls:>7}{total / 1e3:>11.3f}"
            f"{total / calls / 1e3:>10.3f}{mx / 1e3:>10.3f}{pct:>7.1f}%"
        )
    lines.append(
        f"-- {sum(r[0] for r in agg.values())} spans, "
        f"{len(agg)} distinct names, wall {wall_us / 1e3:.3f} ms"
    )
    return "\n".join(lines)


def _selftest() -> int:
    """End-to-end recorder -> exporter -> parser check on a synthetic
    workload with nested and cross-thread spans."""
    import os
    import tempfile
    import threading
    import time

    from . import tracing

    tracing.trace_enable(buffer_size=4096)
    tracing.trace_reset()
    try:
        with tracing.span("selftest.parent", step=1):
            with tracing.span("selftest.child"):
                time.sleep(0.002)
            with tracing.span("selftest.child"):
                time.sleep(0.001)

        def worker():
            with tracing.span("selftest.worker"):
                time.sleep(0.001)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        with tempfile.TemporaryDirectory() as d:
            path = tracing.trace_export(os.path.join(d, "trace.json"))
            events = load_events(path)
    finally:
        tracing.trace_disable()
        tracing.trace_reset()

    by_name = defaultdict(list)
    for ev in events:
        for field in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert field in ev, f"event missing {field!r}: {ev}"
        assert ev["ph"] == "X", ev
        assert ev["dur"] >= 0 and ev["ts"] >= 0, ev
        by_name[ev["name"]].append(ev)
    assert len(by_name["selftest.parent"]) == 1, by_name
    assert len(by_name["selftest.child"]) == 2, by_name
    assert len(by_name["selftest.worker"]) == 1, by_name
    parent = by_name["selftest.parent"][0]
    assert parent["args"] == {"step": 1}, parent
    p0, p1 = parent["ts"], parent["ts"] + parent["dur"]
    for child in by_name["selftest.child"]:
        assert p0 <= child["ts"] and child["ts"] + child["dur"] <= p1, \
            (parent, child)
        assert child["tid"] == parent["tid"]
    assert by_name["selftest.worker"][0]["tid"] != parent["tid"]
    print(summarize(events))
    print("timeline selftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.timeline",
        description="Summarize a chrome://tracing JSON exported by "
                    "paddle_tpu (trace_export / profiler profile_path).")
    ap.add_argument("trace", nargs="?", help="path to trace JSON")
    ap.add_argument("--top", type=int, default=20,
                    help="rows in the summary table (default 20)")
    ap.add_argument("--selftest", action="store_true",
                    help="validate the recorder/exporter round trip")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.trace:
        ap.error("need a trace file (or --selftest)")
    print(summarize(load_events(args.trace), top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
