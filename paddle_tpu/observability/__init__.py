"""paddle_tpu.observability — framework-wide tracing, metrics, logging.

The substrate every perf PR reports against (ISSUE 1):

  - `tracing`: thread-safe span recorder -> chrome://tracing JSON
    (`span()`, `trace_export()`), near-zero cost when disabled. The role
    the reference's platform/profiler.cc + device_tracer.cc played.
  - `metrics`: always-on counter/gauge/histogram registry with dict
    snapshot + Prometheus text export. BENCH_*.json embeds a snapshot so
    the perf trajectory carries framework counters (jit compiles, cache
    hits, RPC bytes), not just wall clock.
  - `log`: the `paddle_tpu.*` logger tree (PADDLE_TPU_LOG_LEVEL).
  - `timeline`: `python -m paddle_tpu.observability.timeline trace.json`
    prints a top-N span summary (tools/timeline.py's role); `merge`
    combines per-process shards into ONE clock-aligned timeline
    (ISSUE 3); `--selftest` round-trips both and is wired into tier-1.
  - `debug_server`: stdlib HTTP live introspection (/metrics /healthz
    /tracez /statusz) — PADDLE_TPU_DEBUG_PORT attaches it to any
    serving pserver/master without code changes.

Cross-process tracing (ISSUE 3): spans carry trace_id/span_id/parent,
the RPC layers stamp a `__trace__` header into every frame, server
handlers adopt it and answer chrome flow events, so a merged timeline
draws client→server arrows across processes.

Env flags: PADDLE_TPU_TRACE=1 enables span recording at import;
PADDLE_TPU_TRACE_BUFFER sizes the ring buffer (default 65536 spans);
PADDLE_TPU_TRACE_DIR=<dir> exports this process's shard to
<dir>/trace-<pid>.json at exit; PADDLE_TPU_DEBUG_PORT starts the debug
HTTP server when a pserver/master serves.
`fluid.profiler.profiler(profile_path=...)` also enables tracing for its
scope and exports on exit, so the legacy API gained the exporter for
free.
"""
from . import metrics, tracing  # noqa: F401
from .log import get_logger  # noqa: F401
from .metrics import (  # noqa: F401
    counter,
    gauge,
    histogram,
    prometheus_text,
    reset_all,
    reset_metrics,
    snapshot,
)
from .tracing import (  # noqa: F401
    span,
    trace_enable,
    trace_disable,
    trace_enabled,
    trace_events,
    trace_export,
    trace_reset,
)
