"""Live introspection HTTP server (ISSUE 3): inspect a running
pserver/master/trainer WITHOUT killing it.

Stdlib-only (http.server on an ephemeral port), serving:

  /healthz   liveness probe — 200 "ok"
  /metrics   the whole paddle_tpu.observability registry in Prometheus
             exposition text (RPC latency histograms, jit compile
             counters, tracing.dropped_spans, ...)
  /tracez    recent spans from the trace ring buffer as JSON
             (?n=100 bounds the tail; includes enable state + drops)
  /statusz   process status JSON: flags, jax backend/devices, uptime,
             plus every registered status provider (the pserver adds
             its param table + heartbeat ages, the master its queue
             stats, the RPC server its dedup-cache occupancy, and a
             ServingServer its "serving:<port>" section — models,
             versions, bucket ladders, queue depths)

Two ways in:

  - explicit: ``DebugServer().start(port=0)`` → (host, port)
  - env flag: ``PADDLE_TPU_DEBUG_PORT=0`` (ephemeral) or ``=8321``
    makes ``ParameterServer.serve()`` / ``MasterService.serve()`` start
    the PROCESS-SHARED server via ``maybe_serve_from_env()`` and attach
    their status providers; the bound address is logged at WARNING so
    operators find it in any log tail.

Read-only by design: every endpoint is a GET with no side effects, so
exposing it on localhost during an incident can't corrupt training
state. It binds 127.0.0.1 by default — the introspection surface is for
the operator on the box (or a port-forward), not the open network.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from . import metrics as _metrics, tracing as _tracing
from .log import get_logger

__all__ = ["DebugServer", "maybe_serve_from_env", "shared_server",
           "add_status", "remove_status"]

_log = get_logger("debug")

_START_TIME = time.time()


def _json_safe(v):
    """Best-effort JSON coercion: status providers return whatever is
    handy (numpy ints, tuples, sets); the wire format must never raise."""
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_json_safe(x) for x in v]
    if isinstance(v, (str, bool)) or v is None:
        return v
    if isinstance(v, (int, float)):
        return v
    try:  # numpy scalars expose item(); anything else degrades to repr
        return v.item()
    except AttributeError:
        return repr(v)


def _flags_view() -> Dict[str, Any]:
    from ..fluid.flags import FLAGS

    return {k: _json_safe(FLAGS[k]) for k in sorted(FLAGS)}


def _jax_view() -> Dict[str, Any]:
    try:
        import jax

        return {"backend": jax.default_backend(),
                "device_count": jax.device_count(),
                "devices": [str(d) for d in jax.devices()]}
    except Exception as e:  # jax may be mid-init or absent in tools
        return {"error": f"{type(e).__name__}: {e}"}


class DebugServer:
    """One HTTP introspection server; `add_status(name, fn)` registers a
    zero-arg callable whose (JSON-safe-coerced) return value appears
    under that name in /statusz. Provider failures are reported inline
    per provider — one broken subsystem must not blank the whole page."""

    def __init__(self):
        self._providers: Dict[str, Callable[[], Any]] = {}
        self._mu = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None

    def add_status(self, name: str, fn: Callable[[], Any]):
        with self._mu:
            self._providers[str(name)] = fn

    def remove_status(self, name: Optional[str]):
        if name is None:
            return
        with self._mu:
            self._providers.pop(str(name), None)

    # -- endpoint payloads -------------------------------------------------
    def _statusz(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "uptime_s": round(time.time() - _START_TIME, 3),
            "process_label": _tracing.process_label(),
            "flags": _flags_view(),
            "jax": _jax_view(),
            "tracing": {
                "enabled": _tracing.trace_enabled(),
                "buffer_capacity": _tracing.buffer_capacity(),
                "dropped_spans": _tracing.dropped_spans(),
            },
        }
        with self._mu:
            providers = dict(self._providers)
        for name, fn in sorted(providers.items()):
            try:
                out[name] = _json_safe(fn())
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    @staticmethod
    def _tracez(n: int) -> Dict[str, Any]:
        events = _tracing.trace_events()
        return {
            "enabled": _tracing.trace_enabled(),
            "buffer_capacity": _tracing.buffer_capacity(),
            "buffered": len(events),
            "dropped_spans": _tracing.dropped_spans(),
            "recent": events[-n:] if n > 0 else [],
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0
              ) -> Tuple[str, int]:
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                u = urlparse(self.path)
                try:
                    if u.path == "/healthz":
                        self._send(200, "text/plain; charset=utf-8", "ok\n")
                    elif u.path == "/metrics":
                        self._send(200, "text/plain; version=0.0.4",
                                   _metrics.prometheus_text())
                    elif u.path == "/tracez":
                        q = parse_qs(u.query)
                        n = int(q.get("n", ["100"])[0])
                        self._send(200, "application/json",
                                   json.dumps(srv._tracez(n)))
                    elif u.path == "/statusz":
                        self._send(200, "application/json",
                                   json.dumps(srv._statusz()))
                    else:
                        self._send(404, "text/plain; charset=utf-8",
                                   "not found; try /healthz /metrics "
                                   "/tracez /statusz\n")
                except (BrokenPipeError, ConnectionError):
                    pass  # scraper went away mid-response

            def _send(self, code: int, ctype: str, body: str):
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):  # stdlib logs to stderr
                _log.debug("debug-server %s", fmt % args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        t = threading.Thread(target=self._server.serve_forever,
                             daemon=True, name="paddle-tpu-debug-http")
        t.start()
        return self._server.server_address

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


# --- the process-shared instance the env flag controls -------------------

_shared: Optional[DebugServer] = None
_shared_mu = threading.Lock()


def shared_server() -> Optional[DebugServer]:
    """The env-flag-started process-wide server (None if never started)."""
    return _shared


def ensure_shared(port: int = 0, host: str = "127.0.0.1") -> DebugServer:
    """Start (once) and return the process-shared server. Subsequent
    calls — a second pserver in the same process, the master — reuse it:
    one port per process, many status providers."""
    global _shared
    with _shared_mu:
        if _shared is None:
            s = DebugServer()
            addr = s.start(host, port)
            _shared = s
            _log.warning("debug server listening on http://%s:%d "
                         "(/healthz /metrics /tracez /statusz)", *addr)
        return _shared


def maybe_serve_from_env() -> Optional[DebugServer]:
    """PADDLE_TPU_DEBUG_PORT unset/empty → None; "0" → shared server on
    an ephemeral port; "<port>" → that port. Called by every serve()
    entry point so attaching introspection needs no code changes.

    Never raises: a malformed port value or a bind failure (fixed port
    already taken by another process on the host) degrades to a logged
    error — the OPTIONAL introspection layer must not take down the
    data-plane server that asked for it."""
    port = os.environ.get("PADDLE_TPU_DEBUG_PORT")
    if port is None or port.strip() == "":
        return None
    try:
        return ensure_shared(int(port))
    except (ValueError, OSError) as e:
        _log.error("debug server disabled: PADDLE_TPU_DEBUG_PORT=%r "
                   "unusable (%s: %s)", port, type(e).__name__, e)
        return None


def add_status(name: str, fn: Callable[[], Any]):
    """Register on the shared server if it is running (no-op otherwise —
    callers don't need to care whether the operator enabled the flag)."""
    if _shared is not None:
        _shared.add_status(name, fn)


def remove_status(name: Optional[str]):
    if _shared is not None:
        _shared.remove_status(name)
