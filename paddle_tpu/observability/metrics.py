"""Process-wide metrics registry: counters, gauges, and histograms with
p50/p95/p99, exportable as a dict snapshot or Prometheus text format.

Unlike tracing, metrics are ALWAYS on — a counter increment is an int
add under a per-metric lock, cheap enough for every hot path it guards
(jit cache hits, RPC bytes, pserver pushes). The registry is flat and
name-keyed; `counter(name)` etc. are find-or-create and cache-friendly
(call once at module/instance setup, keep the handle, `.inc()` per
event).

Histogram keeps a bounded reservoir (uniform reservoir sampling past the
cap) so a million RPC latencies cost ~4 KB, while count/sum/min/max stay
exact.
"""
from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional, Union

__all__ = [
    "Counter", "Gauge", "Histogram",
    "counter", "gauge", "histogram",
    "snapshot", "prometheus_text", "reset_metrics", "reset_all",
]

_registry: Dict[str, "_Metric"] = {}  # guarded-by: _registry_mu
_registry_mu = threading.Lock()


def _registered() -> List[tuple]:
    """Consistent (name, metric) snapshot, sorted. Every iterator goes
    through here: iterating the live dict while a find-or-create on
    another thread inserts raises RuntimeError mid-scrape (guards-lint
    finding on snapshot/prometheus_text/reset_metrics)."""
    with _registry_mu:
        return sorted(_registry.items())


def _sanitize(name: str) -> str:
    """Prometheus metric names allow [a-zA-Z0-9_:]; our dotted span-style
    names map dots and dashes to underscores."""
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


class _Metric:
    kind = "metric"

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()

    def value(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def prom_lines(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic event count."""

    kind = "counter"

    def __init__(self, name: str):
        super().__init__(name)
        self._n = 0

    def inc(self, n: int = 1):
        with self._mu:
            self._n += n

    def value(self) -> int:
        return self._n

    def reset(self):
        with self._mu:
            self._n = 0

    def prom_lines(self):
        n = _sanitize(self.name)
        return [f"# TYPE {n} counter", f"{n} {self._n}"]


class Gauge(_Metric):
    """Last-set instantaneous value (records/sec, queue depth, ...)."""

    kind = "gauge"

    def __init__(self, name: str):
        super().__init__(name)
        self._v: float = 0.0

    def set(self, v: float):
        self._v = float(v)  # single store, GIL-atomic

    def add(self, d: float):
        with self._mu:
            self._v += d

    def value(self) -> float:
        return self._v

    def reset(self):
        self._v = 0.0

    def prom_lines(self):
        n = _sanitize(self.name)
        return [f"# TYPE {n} gauge", f"{n} {self._v}"]


class Histogram(_Metric):
    """Observations with exact count/sum/min/max and reservoir-sampled
    percentiles (p50/p95/p99). `reservoir` caps memory; below the cap the
    percentiles are exact."""

    kind = "histogram"

    def __init__(self, name: str, reservoir: int = 2048):
        super().__init__(name)
        self._cap = max(16, int(reservoir))
        self._vals: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._rng = random.Random(0xC0FFEE)

    def observe(self, v: float):
        v = float(v)
        with self._mu:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if len(self._vals) < self._cap:
                self._vals.append(v)
            else:  # uniform reservoir: each of the N observations has
                # cap/N probability of being retained
                j = self._rng.randrange(self._count)
                if j < self._cap:
                    self._vals[j] = v

    @staticmethod
    def _rank(vals: List[float], q: float) -> float:
        """Nearest-rank percentile over an already-sorted list."""
        rank = max(0, min(len(vals) - 1,
                          int(round(q / 100.0 * (len(vals) - 1)))))
        return vals[rank]

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the reservoir, q in [0, 100]."""
        with self._mu:
            vals = sorted(self._vals)
        if not vals:
            return 0.0
        return self._rank(vals, q)

    def value(self) -> Dict[str, float]:
        # one lock hold + one sort for a CONSISTENT stats set (three
        # percentile() calls would sort thrice and could interleave with
        # concurrent observes)
        with self._mu:
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
            vals = sorted(self._vals)
        if count == 0:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "avg": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
            "avg": total / count,
            "p50": self._rank(vals, 50),
            "p95": self._rank(vals, 95),
            "p99": self._rank(vals, 99),
        }

    def reset(self):
        with self._mu:
            self._vals = []
            self._count = 0
            self._sum = 0.0
            self._min = float("inf")
            self._max = float("-inf")

    def prom_lines(self):
        n = _sanitize(self.name)
        v = self.value()
        return [
            f"# TYPE {n} summary",
            f'{n}{{quantile="0.5"}} {v["p50"]}',
            f'{n}{{quantile="0.95"}} {v["p95"]}',
            f'{n}{{quantile="0.99"}} {v["p99"]}',
            f"{n}_sum {v['sum']}",
            f"{n}_count {v['count']}",
        ]


def _get(name: str, cls, **kw):
    # lint: allow-unguarded(_registry) — deliberate double-checked read:
    # dict.get is GIL-atomic, a hit avoids the lock on the hot
    # find-or-create path, and a miss re-validates under _registry_mu
    m = _registry.get(name)
    if m is not None:
        if not isinstance(m, cls):
            raise TypeError(
                f"metric '{name}' already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m
    with _registry_mu:
        m = _registry.get(name)
        if m is None:
            m = _registry[name] = cls(name, **kw)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric '{name}' already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m


def counter(name: str) -> Counter:
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    return _get(name, Gauge)


def histogram(name: str, reservoir: int = 2048) -> Histogram:
    return _get(name, Histogram, reservoir=reservoir)


def snapshot(prefix: str = "", skip_zero: bool = False) -> Dict[str, Any]:
    """name -> value dict of every registered metric (histograms as their
    stats dict). `prefix` filters; `skip_zero` drops zero counters /
    empty histograms (the compact form BENCH artifacts embed)."""
    out: Dict[str, Any] = {}
    for name, m in _registered():
        if prefix and not name.startswith(prefix):
            continue
        v = m.value()
        if skip_zero:
            if isinstance(v, dict) and not v.get("count"):
                continue
            if not isinstance(v, dict) and not v:
                continue
        out[name] = v
    return out


def prometheus_text() -> str:
    lines: List[str] = []
    for _name, m in _registered():
        lines.extend(m.prom_lines())
    return "\n".join(lines) + ("\n" if lines else "")


def reset_metrics(prefix: str = ""):
    """Zero every metric (or those under `prefix`). Handles stay valid —
    callers keep their cached Counter/Gauge/Histogram objects."""
    for name, m in _registered():
        if prefix and not name.startswith(prefix):
            continue
        m.reset()


def reset_all():
    """Test-isolation helper: zero every registered metric AND the trace
    recorder's buffer/drop counter in one call, so module-level counter
    handles created by an earlier test (or an earlier PROCESS phase)
    can't bleed absolute values into the next test's assertions.
    Registrations survive — only values reset — so cached handles keep
    feeding the same (now-zeroed) metrics. tests/conftest.py runs this
    autouse before every test."""
    reset_metrics()
    from . import tracing

    tracing.trace_reset()
