"""Shared logger namespace. Every framework component logs under
`paddle_tpu.<component>` so one env knob (PADDLE_TPU_LOG_LEVEL) controls
the whole tree and library users can re-route it with standard logging
config. A StreamHandler is attached to the root `paddle_tpu` logger only
if the application hasn't configured one — never hijack an existing
logging setup."""
from __future__ import annotations

import logging
import os

_configured = False


def get_logger(name: str = "") -> logging.Logger:
    global _configured
    root = logging.getLogger("paddle_tpu")
    if not _configured:
        _configured = True
        if not root.handlers and not logging.getLogger().handlers:
            h = logging.StreamHandler()
            h.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s"))
            root.addHandler(h)
        level = os.environ.get("PADDLE_TPU_LOG_LEVEL", "WARNING").upper()
        root.setLevel(getattr(logging, level, logging.WARNING))
    return root.getChild(name) if name else root
