"""Reader creators (reference python/paddle/reader/creator.py: np_array,
text_file, recordio)."""
from __future__ import annotations

import pickle
from typing import Sequence, Union


def np_array(x):
    """Reader yielding rows of a numpy array."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path: str):
    """Reader yielding stripped lines of a text file."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths: Union[str, Sequence[str]], num_threads: int = 2,
             queue_capacity: int = 256):
    """Reader over recordio file(s) written by
    fluid.recordio_writer.convert_reader_to_recordio_file* — unpickles each
    record. Multiple paths stream through the native threaded prefetcher
    (csrc/recordio.cc rio_multi_reader)."""
    if isinstance(paths, str):
        paths = [p for p in paths.split(",") if p]

    def reader():
        from ..native.recordio import multi_file_reader

        for rec in multi_file_reader(list(paths), n_threads=num_threads,
                                     queue_capacity=queue_capacity):
            yield pickle.loads(rec)

    return reader


def cloud_reader(paths: Union[str, Sequence[str]], master_endpoint,
                 unpickle: bool = True):
    """Master-fed fault-tolerant reader (reference
    python/paddle/v2/reader/creator.py:91 cloud_reader — there, recordio
    chunks are leased from the Go master found via etcd; here from
    distributed.master.MasterService over its TCP RPC). The first reader
    to arrive registers the dataset; every worker then drains leased
    tasks — a worker that dies mid-task has its lease expire and the
    task re-queued, so records are processed at-least-once across the
    fleet."""
    if isinstance(paths, str):
        paths = [p for p in paths.split(",") if p]

    def reader():
        from ..distributed.master import MasterClient

        client = MasterClient(addr=master_endpoint)
        try:
            # idempotent on the service side: the first worker registers,
            # later workers (or later passes) join the existing queues
            client.set_dataset(list(paths))
            if client.all_done():
                # previous pass exhausted: this reader() call is an epoch —
                # re-queue the finished tasks (no-op race-safe: only one
                # caller's new_pass returns True, everyone then drains)
                client.new_pass()
            for rec in client.records():
                yield pickle.loads(rec) if unpickle else rec
        finally:
            client.close()

    return reader
