"""Reader creators (reference python/paddle/reader/creator.py: np_array,
text_file, recordio)."""
from __future__ import annotations

import pickle
from typing import Sequence, Union


def np_array(x):
    """Reader yielding rows of a numpy array."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path: str):
    """Reader yielding stripped lines of a text file."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths: Union[str, Sequence[str]], num_threads: int = 2,
             queue_capacity: int = 256):
    """Reader over recordio file(s) written by
    fluid.recordio_writer.convert_reader_to_recordio_file* — unpickles each
    record. Multiple paths stream through the native threaded prefetcher
    (csrc/recordio.cc rio_multi_reader)."""
    if isinstance(paths, str):
        paths = [p for p in paths.split(",") if p]

    def reader():
        from ..native.recordio import multi_file_reader

        for rec in multi_file_reader(list(paths), n_threads=num_threads,
                                     queue_capacity=queue_capacity):
            yield pickle.loads(rec)

    return reader
