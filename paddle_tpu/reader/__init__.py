"""Functional reader combinators (reference python/paddle/reader/decorator.py:
map_readers:29, shuffle:51, chain:86, compose:118, buffered:165, firstn:208;
batch.py). A reader is a zero-arg callable returning an iterable of samples."""
from __future__ import annotations

import itertools
import queue
import random
import threading

__all__ = [
    "map_readers", "shuffle", "chain", "compose", "buffered", "firstn",
    "batch", "cache", "xmap_readers", "ComposeNotAligned",
]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        rs = [r() for r in readers]
        return itertools.chain(*rs)

    return reader


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned"
                        )
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Prefetch via a daemon thread + bounded queue (reference
    decorator.py:165) — the host-side double-buffer that overlaps input with
    TPU steps."""

    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def cache(reader):
    all_data = []

    def __impl__():
        if not all_data:
            all_data.extend(reader())
        return iter(all_data)

    return __impl__


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool mapped reader (reference decorator.py xmap_readers)."""

    def data_reader():
        import concurrent.futures

        with concurrent.futures.ThreadPoolExecutor(process_num) as pool:
            it = reader()
            for out in pool.map(mapper, it):
                yield out

    return data_reader


def batch(reader, batch_size, drop_last: bool = False):
    """Minibatching (reference python/paddle/batch.py)."""

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
