"""CLI: ``python -m paddle_tpu.mesh --selftest`` (in-process proof of
the mesh layer on the virtual CPU mesh — tools/check.py runs it) and
``--describe AXES`` (print a spec's axes/size and the stock rule sets'
assignments for a few representative names)."""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # the selftest needs the 8-device virtual mesh BEFORE jax inits
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.mesh")
    ap.add_argument("--selftest", action="store_true",
                    help="run the in-process mesh selftest")
    ap.add_argument("--describe", metavar="AXES", default=None,
                    help="parse 'dp=2,tp=4' and print the mesh + stock "
                         "rule assignments")
    args = ap.parse_args(argv)

    if args.describe:
        from . import MeshSpec, decoder_rules, transformer_rules

        ms = MeshSpec.parse(args.describe)
        print(f"mesh: {ms} (devices: {ms.size})")
        tr = transformer_rules()
        dr = decoder_rules()
        for name, ndim in (("enc0.self.q.w", 2), ("enc0.self.out.w", 2),
                           ("enc0.ff1.w", 2), ("enc0.a.ln.scale", 1)):
            print(f"  train {name:24s} -> {tr.spec_for(name, ndim)}")
        for name, ndim in (("layer0/wk", 2), ("layer0/wo", 2),
                           ("tok_emb", 2), ("lnf/0", 1)):
            print(f"  serve {name:24s} -> {dr.spec_for(name, ndim)}")
        return 0

    if args.selftest:
        from .selftest import run_selftest

        return 1 if run_selftest() else 0

    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
