"""Mesh observability (ISSUE 15 satellite): gauges for the active mesh,
per-collective-kind compile counters, and the ``/statusz`` mesh section.

Host code cannot time individual device collectives — XLA fuses them
into the step program — but it CAN count them exactly at compile time
(``jax_compat.collective_counts`` over the lowered text) and carry the
counts on the step span. So the observability contract is:

  - ``mesh.devices`` / ``mesh.axes`` gauges describe the active mesh
    (device count / axis count), per-axis sizes ride the f-string
    family ``mesh.axis.<name>`` (the fleet ``fleet.replica_up.<rid>``
    discipline);
  - ``mesh.collectives.<kind>`` counters accumulate per COMPILED
    sharded executable — a communication regression (an extra
    all-gather from a changed spec) moves a counter, not just a wall
    clock;
  - ``mesh.sharded_steps`` counts sharded step dispatches, and the
    executor's step span carries ``collectives=`` so traces show what
    each program shipped over ICI;
  - ``/statusz`` grows a ``mesh`` section (axes, device count, compile
    collective totals) via the process-shared debug server.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ..observability import debug_server as _debug
from ..observability import metrics as _metrics

__all__ = ["note_mesh", "note_sharded_compile", "collective_counts",
           "mesh_status", "sharded_step_counter"]

# re-exported so callers needing the counting rule import ONE module
from ..jax_compat import collective_counts  # noqa: E402  (re-export)

_m_devices = _metrics.gauge("mesh.devices")
_m_axes = _metrics.gauge("mesh.axes")
_m_sharded_steps = _metrics.counter("mesh.sharded_steps")
_m_sharded_compiles = _metrics.counter("mesh.sharded_compiles")
# one counter per collective kind the partitioner can insert; names
# must match jax_compat._COLLECTIVE_OPS keys
_m_collectives = {
    kind: _metrics.counter(f"mesh.collectives.{kind}")
    for kind in ("all_reduce", "all_gather", "reduce_scatter",
                 "collective_permute", "all_to_all")
}


def sharded_step_counter():
    """The ``mesh.sharded_steps`` counter (executors inc it per sharded
    dispatch; tests read it)."""
    return _m_sharded_steps


class _MeshStats:
    """Process-wide record of active meshes for /statusz — written by
    ``note_mesh``/``note_sharded_compile`` from whatever thread builds
    or compiles (executor callers, serving scheduler), read by the
    debug server's scrape thread."""

    def __init__(self):
        self._mu = threading.Lock()
        self._meshes: Dict[str, Dict[str, Any]] = {}  # guarded-by: _mu
        self._collective_totals: Dict[str, int] = {}  # guarded-by: _mu

    def note_mesh(self, label: str, axes: Dict[str, int]):
        with self._mu:
            self._meshes[str(label)] = dict(axes)
        # (re-)register every time: add_status no-ops without a shared
        # debug server, and the server may attach AFTER the first mesh
        # was built — idempotent dict set either way
        _debug.add_status("mesh", self.status)

    def note_collectives(self, counts: Dict[str, int]):
        with self._mu:
            for k, v in counts.items():
                self._collective_totals[k] = \
                    self._collective_totals.get(k, 0) + int(v)

    def status(self) -> Dict[str, Any]:
        with self._mu:
            meshes = {k: dict(v) for k, v in self._meshes.items()}
            totals = dict(self._collective_totals)
        return {
            "meshes": meshes,
            "collectives_compiled": totals,
            "sharded_steps": _m_sharded_steps.value(),
            "sharded_compiles": _m_sharded_compiles.value(),
        }


_stats = _MeshStats()


def note_mesh(mesh, label: str = "default") -> None:
    """Record an ACTIVE mesh: sets the ``mesh.devices``/``mesh.axes``
    gauges and the per-axis ``mesh.axis.<name>`` family, and registers
    the /statusz section on first use. ``mesh`` is a built jax Mesh (or
    anything with ``axis_names`` + ``devices``)."""
    axes = dict(zip(mesh.axis_names,
                    (int(s) for s in mesh.devices.shape)))
    _m_devices.set(int(mesh.devices.size))
    _m_axes.set(len(axes))
    for name, size in axes.items():
        _metrics.gauge(f"mesh.axis.{name}").set(size)
    _stats.note_mesh(label, axes)


def note_sharded_compile(lowered_text: str,
                         counts: Optional[Dict[str, int]] = None
                         ) -> Dict[str, int]:
    """Account one freshly COMPILED sharded executable: count its
    collectives (or take pre-counted ``counts``), bump the
    ``mesh.collectives.*`` counters and ``mesh.sharded_compiles``, and
    return the counts so the caller can stamp its step span."""
    if counts is None:
        counts = collective_counts(lowered_text)
    _m_sharded_compiles.inc()
    for kind, n in counts.items():
        c = _m_collectives.get(kind)
        if c is not None:
            c.inc(int(n))
    _stats.note_collectives(counts)
    return counts


def mesh_status() -> Dict[str, Any]:
    """The /statusz ``mesh`` section payload (also directly callable —
    selftests and tests read it without an HTTP round trip)."""
    return _stats.status()
