"""In-process mesh selftest (``python -m paddle_tpu.mesh --selftest``,
wired into tools/check.py): proves the subsystem's core promises on the
virtual CPU mesh without pytest — spec/rules round-trips, a sharded
train step matching single-device numerics, a mesh-sharded decode
engine serving identical tokens with the KV pool sharded over the
kv-head axis, and the sharded checkpoint save/load/corrupt contract.
"""
from __future__ import annotations

import os
import shutil
import tempfile

import numpy as np


def _case_spec_roundtrip():
    from . import MeshSpec

    ms = MeshSpec.parse("dp=2,tp=2,fsdp=2")
    assert ms.size == 8 and ms.axis_names == ("dp", "tp", "fsdp")
    assert MeshSpec.from_dict(ms.to_dict()) == ms
    assert MeshSpec.coerce(str(ms)) == ms
    for bad in ("dp=0", "dp", "dp=x", "dp=2,dp=2"):
        try:
            MeshSpec.parse(bad)
        except ValueError:
            pass
        else:
            raise AssertionError(f"MeshSpec.parse({bad!r}) not refused")


def _case_rules():
    from jax.sharding import PartitionSpec as P

    from . import ShardingRules, decoder_rules, transformer_rules

    r = transformer_rules()
    assert tuple(r.spec_for("enc0.self.q.w", 2)) == ("fsdp", "tp")
    assert tuple(r.spec_for("enc0.self.out.w_moment1_0", 2)) == \
        ("tp", "fsdp")
    assert tuple(r.spec_for("enc0.self.q.w_beta1_pow_acc_0", 0)) == ()
    rt = ShardingRules.from_dict(r.to_dict())
    assert tuple(rt.spec_for("enc0.self.q.w", 2)) == ("fsdp", "tp")
    d = decoder_rules()
    assert tuple(d.spec_for("layer0/wk", 2)) == (None, "tp")
    assert tuple(d.spec_for("layer0/ln1/0", 1)) == ()
    assert tuple(d.feed_spec(2)) == ()
    assert tuple(ShardingRules([(r"x", P("a"))], batch_axis="b")
                 .feed_spec(2)) == ("b", None)


def _case_sharded_train_parity():
    """A seeded fc train step on dp=2 x fsdp=2 x tp=2 matches the
    single-device run (f32 reduction reorder tolerance)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.fluid.framework import Program, program_guard
    from paddle_tpu.observability import metrics as _metrics

    from . import MeshSpec, ShardingRules
    from jax.sharding import PartitionSpec as P

    def build(scope):
        main, startup = Program(), Program()
        main.random_seed = startup.random_seed = 11
        from paddle_tpu.fluid import unique_name

        with unique_name.guard(), program_guard(main, startup):
            x = layers.data(name="x", shape=[16], dtype="float32")
            y = layers.data(name="y", shape=[4], dtype="float32")
            h = layers.fc(input=x, size=32, act="tanh")
            out = layers.fc(input=h, size=4)
            loss = layers.mean(layers.square_error_cost(input=out,
                                                        label=y))
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        return main, loss

    rng = np.random.RandomState(0)
    xs = rng.randn(8, 16).astype(np.float32)
    ys = np.tanh(xs[:, :4])
    feed = {"x": xs, "y": ys}

    scope1 = fluid.Scope()
    with fluid.scope_guard(scope1):
        main, loss = build(scope1)
        (ref,) = fluid.Executor().run(main, feed=feed, fetch_list=[loss])

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        main, loss = build(scope2)
        rules = ShardingRules(
            rules=[(r"fc_0\.w", P("fsdp", "tp")),
                   (r"fc_1\.w", P("tp", "fsdp")),
                   (r".", P("fsdp"))],
            batch_axis="dp")
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main,
            mesh=MeshSpec.parse("dp=2,tp=2,fsdp=2"),
            sharding_plan=rules)
        (sh,) = pe.run(fetch_list=[loss], feed=feed)
    rel = abs(float(np.ravel(sh)[0]) - float(np.ravel(ref)[0])) / \
        max(abs(float(np.ravel(ref)[0])), 1e-12)
    assert rel < 1e-3, f"sharded-vs-single rel err {rel}"
    snap = _metrics.snapshot()
    assert snap["mesh.sharded_steps"] >= 1
    assert snap["mesh.collectives.all_reduce"] >= 1, \
        "dp training step compiled without an all-reduce?"


def _case_sharded_decode():
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.serving.decode import DecodeEngine, DecoderSpec

    spec = DecoderSpec(vocab=32, d_model=32, n_heads=4, n_kv_heads=4,
                       n_layers=1)
    e0 = DecodeEngine(spec, name="st-ref", slots=[1], num_pages=16,
                      page_size=4, max_seq_len=16, mesh="")
    ref = e0.generate([3, 5, 7], max_new_tokens=5)
    e0.stop(drain=True)
    e1 = DecodeEngine(spec, name="st-mesh", slots=[1], num_pages=16,
                      page_size=4, max_seq_len=16, mesh="tp=2")
    assert "tp" in str(e1.cache.k.sharding.spec), e1.cache.k.sharding
    warm = _metrics.snapshot()["serving.decode.compiles"]
    out = e1.generate([3, 5, 7], max_new_tokens=5)
    out2 = e1.generate([1, 2], max_new_tokens=4)
    assert out["tokens"] == ref["tokens"], (out, ref)
    assert out2["tokens"]
    post = _metrics.snapshot()["serving.decode.compiles"] - warm
    assert post == 0, f"sharded churn minted {post} post-warm compiles"
    assert e1.stats()["mesh"] == {"tp": 2}
    e1.stop(drain=True)
    try:
        DecodeEngine(DecoderSpec(vocab=32, d_model=48, n_heads=6,
                                 n_kv_heads=3, n_layers=1),
                     name="st-bad", mesh="tp=2", warm=False)
    except ValueError:
        pass
    else:
        raise AssertionError("indivisible kv heads not refused")


def _case_sharded_checkpoint():
    from paddle_tpu.checkpoint import (CheckpointCorruptError,
                                       load_decoder_checkpoint,
                                       load_sharded_checkpoint,
                                       save_decoder_checkpoint)
    from paddle_tpu.serving.decode import DecoderSpec, \
        build_decoder_params

    spec = DecoderSpec(vocab=32, d_model=32, n_heads=4, n_kv_heads=4,
                       n_layers=1)
    params = build_decoder_params(spec)
    d = tempfile.mkdtemp(prefix="mesh_selftest_ck_")
    try:
        save_decoder_checkpoint(d, spec, params, mesh_axes="tp=2",
                                shard_axis="tp")
        shard_files = [n for n in os.listdir(d) if ".s" in n]
        assert len(shard_files) == 2, shard_files
        _, loaded = load_decoder_checkpoint(d)
        want = np.asarray(params["layer0"]["wk"])
        assert np.array_equal(np.asarray(loaded["layer0"]["wk"]), want)
        tree1, _ = load_sharded_checkpoint(d, shard=1)
        assert np.array_equal(np.asarray(tree1["layer0"]["wk"]),
                              want[:, want.shape[1] // 2:])
        victim = os.path.join(
            d, [n for n in shard_files if ".s1." in n][0])
        with open(victim, "r+b") as f:
            f.seek(80)
            f.write(b"\xff\xfe\xfd")
        try:
            load_decoder_checkpoint(d)
        except CheckpointCorruptError as e:
            assert e.tensor and ".s1." in str(e), e
        else:
            raise AssertionError("corrupt shard not named")
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _case_statusz():
    from . import mesh_status

    st = mesh_status()
    assert "meshes" in st and "collectives_compiled" in st
    # the train-parity case above registered the PE mesh
    assert any(v.get("dp") for v in st["meshes"].values()), st


CASES = [
    ("spec_roundtrip", _case_spec_roundtrip),
    ("rules", _case_rules),
    ("sharded_train_parity", _case_sharded_train_parity),
    ("sharded_decode", _case_sharded_decode),
    ("sharded_checkpoint", _case_sharded_checkpoint),
    ("statusz", _case_statusz),
]


def run_selftest(verbose: bool = True) -> int:
    failures = 0
    for name, fn in CASES:
        try:
            fn()
        except Exception as e:  # pragma: no cover - failure reporting
            failures += 1
            print(f"[mesh selftest] FAIL {name}: {type(e).__name__}: {e}")
        else:
            if verbose:
                print(f"[mesh selftest] ok {name}")
    if failures == 0 and verbose:
        print(f"[mesh selftest] {len(CASES)} cases OK")
    return failures
