"""First-class SPMD mesh layer (ISSUE 15): named logical device axes
and name-based parameter sharding as ONE serializable object pair.

The reference framework's multi-device story is replicate-and-allreduce
(`paddle/fluid/framework/parallel_executor.cc`); the TPU-native story is
a named device mesh (``dp`` x ``tp`` x ``fsdp``) plus PartitionSpec
rules over parameter NAMES, with XLA's SPMD partitioner inserting the
ICI collectives. This package owns that layer end to end:

  - ``MeshSpec``: named logical axes -> a jax device Mesh, parse/build/
    serialize (``"dp=2,tp=2,fsdp=2"`` <-> dict <-> Mesh), so a TRAINED
    sharding travels with its artifact (checkpoint meta, serving
    deploys, fleet intents) instead of living in whoever's head built
    the run;
  - ``ShardingRules``: ordered regex -> PartitionSpec assignment over
    var/param names, speaking the same plan protocol ParallelExecutor
    already consumes (``spec_for``/``feed_spec``/``batch_axis``) AND
    JSON round-tripping for export;
  - stock rule sets: ``transformer_rules()`` (dp x tp x fsdp training
    over the fluid transformer's param names), ``decoder_rules()``
    (tensor-parallel serving over the DecoderSpec param tree — KV
    projections shard the kv-head axis, so the paged KV pool shards
    with them);
  - observability (``observe.py``): mesh gauges, per-collective-kind
    compile counters, the ``/statusz`` mesh section.

Downstream: ParallelExecutor accepts a MeshSpec (or FLAGS['mesh_axes'])
and ShardingRules directly; DecodeEngine/load_decoder load mesh-sharded
decoders with the KV pool sharded over the kv-head axis; checkpoint/
sharded.py persists one payload per mesh shard with a merged manifest.
"""
from .spec import (  # noqa: F401
    MeshSpec,
    ShardingRules,
    decoder_rules,
    flatten_param_names,
    shard_param_tree,
    transformer_rules,
)
from .observe import (  # noqa: F401
    collective_counts,
    mesh_status,
    note_mesh,
    note_sharded_compile,
    sharded_step_counter,
)

__all__ = [
    "MeshSpec", "ShardingRules", "transformer_rules", "decoder_rules",
    "flatten_param_names", "shard_param_tree",
    "collective_counts", "note_mesh", "note_sharded_compile",
    "mesh_status",
]
