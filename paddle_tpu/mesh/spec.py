"""MeshSpec + ShardingRules: the serializable half of the mesh layer.

A ``MeshSpec`` is the LOGICAL mesh — ordered named axes with sizes —
independent of any device handle, so it can ride a checkpoint manifest,
a load_decoder RPC, or a fleet intent verbatim. ``build()`` binds it to
real devices (behind ``jax_compat.make_device_mesh`` so one file owns
any topology-ordering skew). ``ShardingRules`` maps var/param NAMES to
PartitionSpecs with ordered first-match regex rules (SNIPPETS [2]/[3]:
name-based spec assignment over dp/fsdp/tp axes) and speaks the
ShardingPlan protocol ParallelExecutor already consumes — one rules
object drives training, serving, and sharded checkpoints.
"""
from __future__ import annotations

import re
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

__all__ = ["MeshSpec", "ShardingRules", "transformer_rules",
           "decoder_rules", "flatten_param_names", "shard_param_tree"]

_AXIS_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


class MeshSpec:
    """Named logical mesh axes, e.g. ``MeshSpec({'dp': 2, 'tp': 4})``.

    Axis ORDER matters (it is the device-array layout order); sizes are
    positive ints. Immutable after construction — every consumer
    (executor, engine, checkpoint) can hold a reference without
    defensive copies.
    """

    def __init__(self, axes: Dict[str, int]):
        if not axes:
            raise ValueError("MeshSpec needs at least one axis")
        clean: "OrderedDict[str, int]" = OrderedDict()
        for name, size in axes.items():
            name = str(name)
            if not _AXIS_RE.match(name):
                raise ValueError(
                    f"mesh axis name {name!r} is not an identifier")
            size = int(size)
            if size < 1:
                raise ValueError(
                    f"mesh axis {name!r} has size {size}; axes must be "
                    ">= 1")
            if name in clean:
                raise ValueError(f"duplicate mesh axis {name!r}")
            clean[name] = size
        self._axes = clean

    # -- views ------------------------------------------------------------
    @property
    def axes(self) -> "OrderedDict[str, int]":
        return OrderedDict(self._axes)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self._axes)

    @property
    def size(self) -> int:
        return int(np.prod(list(self._axes.values()), dtype=np.int64))

    def axis_size(self, name: str) -> int:
        if name not in self._axes:
            raise KeyError(f"mesh has no axis {name!r}; axes: "
                           f"{dict(self._axes)}")
        return self._axes[name]

    def __contains__(self, name: str) -> bool:
        return name in self._axes

    def __eq__(self, other) -> bool:
        return isinstance(other, MeshSpec) and \
            list(self._axes.items()) == list(other._axes.items())

    def __hash__(self):
        return hash(tuple(self._axes.items()))

    def __repr__(self) -> str:
        return f"MeshSpec({dict(self._axes)})"

    # -- parse / serialize -------------------------------------------------
    @classmethod
    def parse(cls, text: str) -> "MeshSpec":
        """``"dp=2,tp=2,fsdp=2"`` -> MeshSpec (the FLAGS['mesh_axes'] /
        CLI spelling). Whitespace-tolerant; typed errors name the bad
        piece."""
        axes: "OrderedDict[str, int]" = OrderedDict()
        for piece in str(text).split(","):
            piece = piece.strip()
            if not piece:
                continue
            if "=" not in piece:
                raise ValueError(
                    f"mesh axis {piece!r} is not 'name=size' (full spec "
                    f"text: {text!r})")
            name, _, size = piece.partition("=")
            name = name.strip()
            if name in axes:
                # catch here: the dict would silently keep one entry
                # and __init__ could never see the duplicate
                raise ValueError(f"duplicate mesh axis {name!r}")
            try:
                axes[name] = int(size.strip())
            except ValueError:
                raise ValueError(
                    f"mesh axis {piece!r} has a non-integer size") \
                    from None
        return cls(axes)

    @classmethod
    def coerce(cls, value) -> "MeshSpec":
        """Accept a MeshSpec, an axes dict, or the 'dp=2,tp=4' string —
        the one rule every mesh= parameter in the repo applies."""
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(value)
        if isinstance(value, str):
            return cls.parse(value)
        raise TypeError(
            f"cannot build a MeshSpec from {type(value).__name__}; pass "
            "a MeshSpec, an axes dict, or a 'dp=2,tp=4' string")

    def to_dict(self) -> Dict[str, Any]:
        return {"axes": [[n, s] for n, s in self._axes.items()]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MeshSpec":
        axes = d.get("axes")
        if not isinstance(axes, (list, tuple)):
            raise ValueError(f"malformed MeshSpec dict {d!r}")
        return cls(OrderedDict((str(n), int(s)) for n, s in axes))

    def __str__(self) -> str:
        return ",".join(f"{n}={s}" for n, s in self._axes.items())

    # -- device binding ----------------------------------------------------
    def build(self, devices: Optional[Sequence[Any]] = None):
        """Bind to real devices -> jax Mesh. Uses the first
        ``self.size`` devices when more are available (the virtual
        8-device CPU mesh under tier-1 frequently outnumbers a 2- or
        4-way test mesh); fewer is a typed error."""
        from ..jax_compat import make_device_mesh

        return make_device_mesh(self.axes, devices=devices)


# --- sharding rules ------------------------------------------------------

def _spec_to_json(spec: P) -> List[Any]:
    out: List[Any] = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append([str(a) for a in e])
        else:
            out.append(str(e))
    return out


def _spec_from_json(entry) -> P:
    dims = []
    for e in entry:
        if e is None:
            dims.append(None)
        elif isinstance(e, (tuple, list)):
            dims.append(tuple(str(a) for a in e))
        else:
            dims.append(str(e))
    return P(*dims)


def _spec_axes(spec: P):
    for e in tuple(spec):
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            for a in e:
                yield str(a)
        else:
            yield str(e)


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules over var/param names; first
    match wins, unmatched names replicate.

    Speaks the plan protocol ``ParallelExecutor`` consumes
    (``spec_for(name, ndim)`` / ``feed_spec(ndim)`` / ``batch_axis`` /
    ``seq_axis`` / ``best_effort``) plus JSON serialization so a rule
    set travels with its artifact. A rule whose spec has more dims than
    the var replicates it (scalar optimizer accumulators derived from a
    param name can't take the param's spec — the ShardingPlan
    convention). Immutable after construction: ``with_rule`` returns a
    new object, so shared references (executor + checkpoint writer +
    statusz) never race a mutation.
    """

    def __init__(self, rules: Sequence[Tuple[str, P]] = (),
                 batch_axis: Optional[str] = "dp",
                 seq_axis: Optional[str] = None,
                 best_effort: bool = True,
                 mesh_spec: Optional[MeshSpec] = None):
        compiled = []
        for pat, spec in rules:
            if not isinstance(spec, P):
                spec = _spec_from_json(spec)
            if mesh_spec is not None:
                for ax in _spec_axes(spec):
                    if ax not in mesh_spec:
                        raise ValueError(
                            f"rule {pat!r} names axis {ax!r} which mesh "
                            f"{mesh_spec} does not have")
            compiled.append((str(pat), re.compile(str(pat)), spec))
        self._rules = tuple(compiled)
        self.batch_axis = batch_axis
        self.seq_axis = seq_axis
        # best_effort (default ON — the plan_fsdp convention): an
        # indivisible dim replicates instead of erroring, so odd-width
        # biases and class-count tails survive any mesh
        self.best_effort = bool(best_effort)

    # -- plan protocol -----------------------------------------------------
    def spec_for(self, name: str, ndim: int) -> P:
        for _, pat, spec in self._rules:
            if pat.search(name):
                if len(spec) > ndim:
                    return P()
                return spec
        return P()

    def feed_spec(self, ndim: int) -> P:
        if self.batch_axis is None or ndim == 0:
            return P()
        if self.seq_axis is not None and ndim >= 2:
            return P(self.batch_axis, self.seq_axis, *([None] * (ndim - 2)))
        return P(self.batch_axis, *([None] * (ndim - 1)))

    # -- construction / serialization -------------------------------------
    def with_rule(self, pattern: str, spec: P) -> "ShardingRules":
        """A new rules object with ``pattern -> spec`` appended (lowest
        priority: earlier rules still win)."""
        rules = [(src, spec_) for src, _, spec_ in self._rules]
        rules.append((pattern, spec))
        return ShardingRules(rules, batch_axis=self.batch_axis,
                             seq_axis=self.seq_axis,
                             best_effort=self.best_effort)

    @property
    def rules(self) -> List[Tuple[Any, P]]:
        """(compiled_pattern, spec) pairs — the ShardingPlan view."""
        return [(pat, spec) for _, pat, spec in self._rules]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rules": [[src, _spec_to_json(spec)]
                      for src, _, spec in self._rules],
            "batch_axis": self.batch_axis,
            "seq_axis": self.seq_axis,
            "best_effort": self.best_effort,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ShardingRules":
        return cls([(str(src), _spec_from_json(spec))
                    for src, spec in d.get("rules", [])],
                   batch_axis=d.get("batch_axis"),
                   seq_axis=d.get("seq_axis"),
                   best_effort=bool(d.get("best_effort", True)))

    @classmethod
    def coerce(cls, value, default=None) -> "ShardingRules":
        """The one rules-coercion rule every mesh_rules= parameter in
        the repo applies: None -> ``default()`` (a zero-arg factory,
        e.g. ``decoder_rules``), a dict -> ``from_dict`` (the wire/
        manifest form), a ShardingRules passes through."""
        if value is None:
            if default is None:
                raise TypeError("mesh rules required (no default)")
            return default()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls.from_dict(value)
        raise TypeError(
            f"cannot build ShardingRules from {type(value).__name__}; "
            "pass a ShardingRules, its to_dict() form, or None")

    def __repr__(self) -> str:
        return (f"ShardingRules({len(self._rules)} rules, "
                f"batch_axis={self.batch_axis!r})")


# --- stock rule sets -----------------------------------------------------

def transformer_rules(dp: str = "dp", fsdp: str = "fsdp", tp: str = "tp"
                      ) -> ShardingRules:
    """dp x tp x fsdp rules for ``models/transformer.py`` param names
    (the SNIPPETS [2] shape: qkv/ff1 column-parallel over tp, out/ff2
    row-parallel, embeddings vocab-sharded — each ALSO dim-sharded over
    fsdp, the ZeRO axis, so per-chip param+optimizer memory divides by
    |fsdp| while GSPMD all-gathers at use). The ``(_\\w+)?$`` tails
    keep Adam/Momentum accumulators sharded alongside their params;
    scalar accumulators replicate via the ndim guard; layer norms
    best-effort-shard dim 0 over fsdp."""
    return ShardingRules(
        rules=[
            (r"\.(q|k|v)\.w(_\w+)?$", P(fsdp, tp)),
            (r"\.ff1\.w(_\w+)?$", P(fsdp, tp)),
            (r"\.out\.w(_\w+)?$", P(tp, fsdp)),
            (r"\.ff2\.w(_\w+)?$", P(tp, fsdp)),
            (r"\.emb(_\w+)?$", P(tp, fsdp)),
            (r"^proj\.w(_\w+)?$", P(fsdp, tp)),
            (r"\.ln\.(scale|bias)(_\w+)?$", P(fsdp)),
            # catch-all FSDP: any remaining tensor shards dim 0 over
            # fsdp (best_effort replicates what cannot divide)
            (r".", P(fsdp)),
        ],
        batch_axis=dp,
    )


def decoder_rules(tp: str = "tp") -> ShardingRules:
    """Tensor-parallel rules for the serving decoder's param tree
    (``build_decoder_params`` names under the checkpoint ``_flatten``
    scheme). Attention projections are column-parallel over tp — wk/wv
    shard the KV-HEAD axis, which is exactly how the paged KV pool
    shards (``[layers, pages, page_size, kv_heads, head_dim]`` over dim
    3) — wo/w2 are row-parallel, the embedding shards its vocab rows.
    Layer norms replicate (tiny, and the ln reduction is over the
    unsharded feature dim)."""
    return ShardingRules(
        rules=[
            (r"/w[qkv]$", P(None, tp)),
            (r"/wo$", P(tp, None)),
            (r"/w1$", P(None, tp)),
            (r"/w2$", P(tp, None)),
            (r"^tok_emb$", P(tp, None)),
        ],
        batch_axis=None,
    )


# --- param-tree helpers --------------------------------------------------

def flatten_param_names(tree, prefix: str = ""):
    """Yield ``(flat_name, leaf)`` pairs under the checkpoint
    ``_flatten`` naming scheme (dict keys and tuple/list indices joined
    with '/'), so ShardingRules written against checkpoint names apply
    to live param trees identically."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from flatten_param_names(v, f"{prefix}{k}/")
        return
    if isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            yield from flatten_param_names(v, f"{prefix}{i}/")
        return
    yield prefix[:-1] if prefix.endswith("/") else prefix, tree


def _tree_map_named(tree, fn, prefix: str = ""):
    if isinstance(tree, dict):
        return {k: _tree_map_named(v, fn, f"{prefix}{k}/")
                for k, v in tree.items()}
    if isinstance(tree, tuple):
        return tuple(_tree_map_named(v, fn, f"{prefix}{i}/")
                     for i, v in enumerate(tree))
    if isinstance(tree, list):
        return [_tree_map_named(v, fn, f"{prefix}{i}/")
                for i, v in enumerate(tree)]
    return fn(prefix[:-1] if prefix.endswith("/") else prefix, tree)


def shard_param_tree(tree, mesh, rules: ShardingRules):
    """device_put every leaf of a param tree per its name-matched rule
    over ``mesh`` (a built jax Mesh). Indivisible dims replicate when
    ``rules.best_effort`` (else typed error naming the tensor) — the
    ParallelExecutor divisibility discipline applied to serving param
    trees. Returns the same tree structure with sharded jax arrays."""
    import jax
    from jax.sharding import NamedSharding

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def _divisible(shape, spec):
        for dim, ax in enumerate(tuple(spec)):
            if ax is None:
                continue
            axes = ax if isinstance(ax, tuple) else (ax,)
            size = int(np.prod([sizes.get(a, 1) for a in axes]))
            if dim >= len(shape) or shape[dim] % size != 0:
                return False
        return True

    def put(name, leaf):
        arr = np.asarray(leaf)
        spec = rules.spec_for(name, arr.ndim)
        for ax in _spec_axes(spec):
            if ax not in sizes:
                raise ValueError(
                    f"param '{name}' rule names axis {ax!r} which mesh "
                    f"axes {sizes} do not have")
        if not _divisible(arr.shape, spec):
            if not rules.best_effort:
                raise ValueError(
                    f"param '{name}' (shape {tuple(arr.shape)}) does "
                    f"not divide over spec {spec} of mesh {sizes}")
            spec = P()
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return _tree_map_named(tree, put)
