"""Multi-host environment (reference capability: trainer/pserver endpoints
lists + gRPC, distribute_transpiler.py:136; TPU-native: the JAX distributed
runtime over DCN)."""
from __future__ import annotations

import os
from typing import Dict, Optional

import jax


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Dict[str, int]:
    """Initialize the multi-host runtime. Arguments default to the standard
    env vars (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID — the role
    the reference fills with PADDLE_INIT_PSERVERS / TRAINER_ID). Single
    process with no coordinator is a no-op (local run).

    After this, jax.devices() spans every host and one pjit/shard_map
    program is the whole cluster's step — there is no separate pserver
    program to build."""
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("PROCESS_ID", "0"))
    if coordinator_address and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return get_world_info()


def get_world_info() -> Dict[str, int]:
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def global_mesh(axes: Dict[str, int], devices=None):
    """Mesh over ALL hosts' devices (axis sizes multiply to the global
    device count). Put the data-parallel axis outermost so it maps across
    hosts (collectives on it cross DCN; inner axes stay on-slice ICI)."""
    from ..parallel import make_mesh

    return make_mesh(axes, devices=devices or jax.devices())
