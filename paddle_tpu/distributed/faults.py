"""Deterministic fault injection for the distributed stack.

The reference framework's fault-tolerance story (go/master lease
timeouts, the etcd-backed pserver surviving trainer churn) is only
trustworthy if failures can be REPRODUCED: a chaos test that depends on
kill timing races proves nothing on the run where the race doesn't
fire. This module is a seeded, env-configurable fault plan that the RPC
framing layer (and any other instrumented site) consults at named
points; a given spec injects exactly the same faults at exactly the
same call indices every run.

Spec grammar (also documented in docs/FAULT_TOLERANCE.md)::

    spec   := entry (';' entry)*
    entry  := 'seed=' INT | rule
    rule   := kind '@' site ':' sel ['=' FLOAT]
    kind   := 'refuse' | 'drop' | 'delay' | 'error' | 'crash'
    site   := dotted name (see below)
    sel    := idx (',' idx)* | 'p' FLOAT | '*'
    idx    := INT | INT '-' INT          # inclusive range

Sites instrumented today (each has its own 0-based call counter):

    connect            RpcClient socket connect         (kind: refuse)
    call.<method>      RpcClient attempt start          (kind: delay)
    send.<method>      before the request frame         (kind: drop —
                       a PARTIAL frame is written, then the connection
                       dies: the server sees a mid-frame disconnect)
    recv.<method>      after the request, before the response (kind:
                       drop — the server processed the call, the reply
                       is lost: the retry/dedup path)
    handler.<method>   server side, before dispatch     (kind: error)
    master.snapshot    MasterService between snapshot tmp-write and
                       rename                           (kind: crash)

`sel` picks which calls fault: explicit indices (``0,3-5``), every call
(``*``), or a seeded coin flip (``p0.1`` — 10% of calls, reproducible
under the plan's ``seed``). Example::

    PADDLE_TPU_FAULTS='seed=7;drop@recv.push_grad:1,3;refuse@connect:0'

Zero overhead when unset: `fire()` is one global read + None check.
Tests install plans with `scoped()`; subprocess workers inherit the env
var. Counters are process-wide and thread-safe — multi-threaded callers
share a site's index sequence, so plans that need per-call determinism
target sites only one thread exercises (or use `p`/`*` selectors whose
assertions don't depend on which thread drew the fault).
"""
from __future__ import annotations

import os
import random
import re
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..observability import metrics as _metrics
from ..observability.log import get_logger

__all__ = [
    "FaultPlan", "InjectedFault", "active", "active_spec", "fire",
    "install", "uninstall", "scoped",
]

_log = get_logger("faults")
_m_injected = _metrics.counter("faults.injected")

KINDS = ("refuse", "drop", "delay", "error", "crash")


class InjectedFault(ConnectionError):
    """A planned fault. Subclasses ConnectionError so client-side retry
    paths and server-side handler guards treat it exactly like the real
    network failure it simulates."""

    def __init__(self, kind: str, site: str, index: int):
        super().__init__(f"injected {kind} at {site}[{index}]")
        self.kind = kind
        self.site = site
        self.index = index


class _Rule:
    __slots__ = ("kind", "site", "indices", "prob", "param")

    def __init__(self, kind: str, site: str, indices: Optional[frozenset],
                 prob: Optional[float], param: Optional[float]):
        self.kind = kind
        self.site = site
        self.indices = indices  # None => '*' or probabilistic
        self.prob = prob        # None => index-based
        self.param = param      # delay seconds, etc.

    def matches(self, index: int, rng: random.Random) -> bool:
        if self.prob is not None:
            return rng.random() < self.prob
        if self.indices is None:  # '*'
            return True
        return index in self.indices


_RULE_RE = re.compile(
    r"^(?P<kind>[a-z]+)@(?P<site>[\w.\-]+):(?P<sel>[^=]+)(?:=(?P<param>.+))?$")


def _parse_sel(sel: str) -> Tuple[Optional[frozenset], Optional[float]]:
    sel = sel.strip()
    if sel == "*":
        return None, None
    if sel.startswith("p"):
        return None, float(sel[1:])
    idx: List[int] = []
    for part in sel.split(","):
        part = part.strip()
        if "-" in part:
            lo, hi = part.split("-", 1)
            idx.extend(range(int(lo), int(hi) + 1))
        else:
            idx.append(int(part))
    return frozenset(idx), None


class FaultPlan:
    """Parsed spec + per-site call counters. Thread-safe; one lock
    serializes counter bumps and the seeded RNG so a spec's behavior is
    a pure function of the call sequence."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        self._rules: Dict[str, List[_Rule]] = {}
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                self.seed = int(entry[5:])
                continue
            m = _RULE_RE.match(entry)
            if m is None:
                raise ValueError(f"bad fault rule {entry!r} "
                                 "(want kind@site:sel[=param])")
            kind = m.group("kind")
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: {KINDS}")
            indices, prob = _parse_sel(m.group("sel"))
            param = float(m.group("param")) if m.group("param") else None
            self._rules.setdefault(m.group("site"), []).append(
                _Rule(kind, m.group("site"), indices, prob, param))
        self._mu = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._rng = random.Random(self.seed)
        self._injected: List[Tuple[str, str, int]] = []

    def fire(self, site: str):
        """Advance `site`'s call counter; sleep (delay) or raise
        InjectedFault if a rule matches this index. Sites with no rules
        still count — an index is the Nth call, rules or not."""
        with self._mu:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
            hit = None
            for rule in self._rules.get(site, ()):
                if rule.matches(index, self._rng):
                    hit = rule
                    break
            if hit is not None:
                self._injected.append((hit.kind, site, index))
        if hit is None:
            return
        _m_injected.inc()
        _log.info("injecting %s at %s[%d]", hit.kind, site, index)
        if hit.kind == "delay":
            time.sleep(hit.param if hit.param is not None else 0.05)
            return
        raise InjectedFault(hit.kind, site, index)

    def counts(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._counts)

    def injected(self) -> List[Tuple[str, str, int]]:
        """(kind, site, index) of every fault fired so far — the
        evidence chaos tests assert against."""
        with self._mu:
            return list(self._injected)


_active: Optional[FaultPlan] = None


def active() -> Optional[FaultPlan]:
    return _active


def active_spec() -> Optional[str]:
    return _active.spec if _active is not None else None


def fire(site: str):
    """Hot-path hook: no plan installed -> one global read and out."""
    plan = _active
    if plan is None:
        return
    plan.fire(site)


def install(spec) -> FaultPlan:
    """Install a plan process-wide (a spec string or a FaultPlan)."""
    global _active
    _active = spec if isinstance(spec, FaultPlan) else FaultPlan(spec)
    return _active


def uninstall():
    global _active
    _active = None


@contextmanager
def scoped(spec):
    """Install a plan for a with-block (tests), restoring the previous
    plan — including None — on exit."""
    global _active
    prev = _active
    plan = install(spec)
    try:
        yield plan
    finally:
        _active = prev


# env-configured plan: parsed once at import so subprocess chaos workers
# (tools/chaos_soak.py, the multiprocess tests) opt in by exporting
# PADDLE_TPU_FAULTS before launch
_env_spec = os.environ.get("PADDLE_TPU_FAULTS")
if _env_spec:
    install(_env_spec)
