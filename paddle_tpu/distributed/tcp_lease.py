"""TCP-backed TTL leases — the etcd-role lease service for deployments
whose shared storage has no trustworthy POSIX locks (VERDICT r4 weak 6:
the realistic multi-machine home for a FileLease is NFS, where flock is
historically the thing that breaks; object-store FUSE mounts don't
implement it at all).

`LeaseServer` is a tiny in-memory lease table served over the same
length-prefixed JSON framing as the master RPC (distributed/rpc.py) —
the role etcd played for the reference (go/master/etcd_client.go
campaign-on-lease; go/pserver/etcd_client.go TTL registration). Run it
once per cluster (it is the coordination point, exactly as etcd was).

`TcpLease` is interface-compatible with election.FileLease
(try_acquire / renew / release / fenced / current), so ElectedMaster
runs unchanged over either:

    em = ElectedMaster(lease_path=None, snapshot_path=...,
                       lease=TcpLease(addr, "master", holder_id))

Fencing: every successful acquire bumps a server-side monotonic term;
`fenced(commit)` verifies holder+term+TTL server-side immediately before
committing, so a deposed leader's late snapshot write raises
MasterDeposed. The check cannot be held across the client-side commit
the way FileLease holds flock, so the term doubles as a fencing TOKEN:
snapshots are term-stamped and MasterService refuses to replace a
higher-term snapshot (see TcpLease.fenced for the full story)."""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional, Tuple

from .rpc import RpcClient, RpcServer


class LeaseServer:
    """In-memory named TTL leases with monotonic fencing terms.

    `state_path` (optional) persists the per-name TERM counters (not the
    ephemeral holders/deadlines) across server restarts. Without it a
    restart resets terms to 1 while term-stamped snapshots on shared
    storage keep their higher terms — recoverable (MasterService adopts
    the higher on-disk term, see master._recover) but it degrades the
    term fencing between post-restart leaders until the counters catch
    up. With it, terms never regress (the role etcd's persisted revision
    counter played)."""

    def __init__(self, state_path: Optional[str] = None):
        self._mu = threading.Lock()
        self._leases = {}  # name -> {holder, deadline, term, endpoint}
        self._server: Optional[RpcServer] = None
        self._state_path = state_path
        if state_path:
            try:
                with open(state_path) as f:
                    for name, term in (json.load(f) or {}).items():
                        self._leases[name] = {"holder": None, "deadline": 0,
                                              "term": int(term),
                                              "endpoint": None}
            except (OSError, ValueError):
                pass  # no/corrupt state: terms restart (degraded fencing)

    def _persist_terms_locked(self):
        if not self._state_path:
            return
        tmp = self._state_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({n: st["term"] for n, st in self._leases.items()},
                          f)
            os.replace(tmp, self._state_path)
        except OSError:
            pass  # persistence is best-effort; the adopt-on-recover path
            # in master._recover keeps the cluster available regardless

    # -- RPC methods ------------------------------------------------------
    def acquire(self, name, holder, ttl, endpoint=None):
        with self._mu:
            st = self._leases.get(name)
            now = time.time()
            if st and st["holder"] not in (None, holder) \
                    and st["deadline"] > now:
                return {"ok": False}
            term = (st["term"] if st and st["holder"] == holder
                    else (st["term"] + 1 if st else 1))
            self._leases[name] = {"holder": holder, "deadline": now + ttl,
                                  "term": term, "endpoint": endpoint}
            if not st or term != st["term"]:
                self._persist_terms_locked()
            return {"ok": True, "term": term}

    def renew(self, name, holder, ttl, endpoint=None):
        with self._mu:
            st = self._leases.get(name)
            if not st or st["holder"] != holder:
                return {"ok": False}
            st["deadline"] = time.time() + ttl
            if endpoint is not None:
                st["endpoint"] = endpoint
            return {"ok": True, "term": st["term"]}

    def release(self, name, holder):
        with self._mu:
            st = self._leases.get(name)
            if st and st["holder"] == holder:
                self._leases[name] = {"holder": None, "deadline": 0,
                                      "term": st["term"], "endpoint": None}
            return {"ok": True}

    def check(self, name, holder, term):
        """The fencing read: does `holder` still hold `name` at `term`
        with an unexpired TTL?"""
        with self._mu:
            st = self._leases.get(name)
            ok = bool(st and st["holder"] == holder
                      and st["term"] == term
                      and st["deadline"] > time.time())
            return {"ok": ok}

    def current(self, name):
        with self._mu:
            st = self._leases.get(name)
            if not st:
                return {}
            out = dict(st)
            # liveness is decided by the SERVER clock — the deadline
            # timestamp is not comparable across hosts under clock skew
            out["live"] = bool(st["holder"]
                               and st["deadline"] > time.time())
            return out

    # -- lifecycle --------------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0):
        self._server = RpcServer({
            "acquire": self.acquire, "renew": self.renew,
            "release": self.release, "check": self.check,
            "current": self.current,
        }, idempotent={
            # all safe to re-run (acquire/renew/release are holder-
            # guarded state convergence, check/current are reads) — and
            # the single-use fail-fast clients TcpLease makes per call
            # can never retransmit anyway, so caching their responses
            # would only grow the dedup cache on the renew hot path
            "acquire", "renew", "release", "check", "current",
        })
        return self._server.serve(host=host, port=port)

    def shutdown(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None


class TcpLease:
    """election.FileLease-compatible lease client over a LeaseServer."""

    def __init__(self, addr: Tuple[str, int], name: str, holder_id: str,
                 ttl: float = 5.0, timeout: float = 10.0):
        self.addr = addr
        self.name = name
        self.holder = holder_id
        self.ttl = float(ttl)
        self._timeout = timeout
        self._term: Optional[int] = None

    @property
    def term(self) -> int:
        """Server-issued fencing term of our current acquisition (0 if
        never acquired). ElectedMaster stamps it into snapshots — the
        backstop for the check-then-commit window documented in
        fenced()."""
        return self._term or 0

    def _call(self, method, *args):
        # retries=0: lease calls must FAIL FAST. A renew that can't reach
        # the server within one timeout means "can't prove we still hold
        # it" — step down NOW; burning a multi-attempt backoff budget
        # here would delay deposition detection far past the TTL.
        client = RpcClient(self.addr, timeout=self._timeout, retries=0)
        try:
            return client.call(method, *args)
        finally:
            client.close()

    def try_acquire(self, endpoint: Optional[Tuple[str, int]] = None) -> bool:
        try:
            r = self._call("acquire", self.name, self.holder, self.ttl,
                           list(endpoint) if endpoint else None)
        except (ConnectionError, OSError):
            return False  # unreachable lease service = cannot lead
        if r.get("ok"):
            self._term = r.get("term")
            return True
        return False

    def renew(self, endpoint: Optional[Tuple[str, int]] = None) -> bool:
        try:
            r = self._call("renew", self.name, self.holder, self.ttl,
                           list(endpoint) if endpoint else None)
        except (ConnectionError, OSError):
            return False  # can't prove we still hold it -> step down
        return bool(r.get("ok"))

    def release(self):
        try:
            self._call("release", self.name, self.holder)
        except (ConnectionError, OSError):
            pass  # TTL will expire it

    def fenced(self, commit: Callable[[], None]):
        """Verify holder+term+TTL server-side, then commit.

        Unlike FileLease.fenced — which holds flock ACROSS commit(), so a
        competing acquire blocks until the commit lands — this is
        check-then-commit: the lease server's mutex cannot extend over a
        client-side commit. A leader that stalls between the check reply
        and commit() can therefore still write after being deposed. That
        residual window is closed by the snapshot TERM: ElectedMaster
        stamps commits with `self.term` and
        MasterService._snapshot_locked refuses to replace a higher-term
        snapshot, so the deposed write loses by term comparison instead
        of by timing (the fencing-token pattern etcd deployments use for
        exactly this reason)."""
        from .master import MasterDeposed

        try:
            r = self._call("check", self.name, self.holder, self._term)
        except (ConnectionError, OSError) as e:
            raise MasterDeposed(f"lease service unreachable: {e}")
        if not r.get("ok"):
            raise MasterDeposed(
                f"{self.holder} no longer holds lease {self.name!r} "
                f"(term {self._term})")
        commit()

    def current(self) -> dict:
        try:
            return self._call("current", self.name)
        except (ConnectionError, OSError):
            return {}


def tcp_endpoint_resolver(addr: Tuple[str, int],
                          name: str) -> Callable[[], Tuple[str, int]]:
    """MasterClient resolver against a LeaseServer (the role of etcd
    re-listing in the reference's pserver clients)."""

    def resolve() -> Tuple[str, int]:
        # fail-fast for the same reason as TcpLease._call: the caller
        # (MasterClient) has its own reconnect/backoff loop around this
        client = RpcClient(addr, timeout=10.0, retries=0)
        try:
            st = client.call("current", name)
        finally:
            client.close()
        ep = st.get("endpoint")
        # "live" is computed on the lease server's clock — never compare
        # the deadline against this host's clock (cross-host skew)
        if not ep or not st.get("live"):
            raise ConnectionError("no live master holds the lease")
        return ep[0], int(ep[1])

    return resolve
