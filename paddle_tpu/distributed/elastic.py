"""Elastic trainer: the checkpoint-resume loop that makes a KILLED
trainer a non-event.

The reference's fault-tolerant cloud story composes three pieces: the
Go master re-serves a dead trainer's data shard when its lease lapses
(go/master/service.go:341), checkpoints carry a crc so a torn write is
detected (go/pserver/service.go:53), and a restarted worker re-registers
and resumes. Our stack has each piece (distributed/master.py leases,
fluid/io.py save/load_checkpoint, membership.WorkerRegistry); this
module is the loop that composes them:

    trainer = ElasticTrainer(master_client, ckpt_dir,
                             main_program=main, scope=scope)
    stats = trainer.run_pass(train_on_task)   # resumes automatically

Per leased task: run the user's training callback, checkpoint the
program's persistables, THEN report task_finished — a crash anywhere in
between re-runs that task from the checkpointed params (at-least-once
training, the same contract lease expiry already gives data delivery).
A restarted process pointed at the same ckpt_dir loads the latest
intact checkpoint, counts an `elastic.resumes`, and keeps draining the
master's queue from wherever the fleet left it.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger
from .master import MasterClient

__all__ = ["ElasticTrainer"]

_log = get_logger("elastic")
_m_resumes = _metrics.counter("elastic.resumes")
_m_tasks = _metrics.counter("elastic.tasks_trained")


class ElasticTrainer:
    """Lease tasks from the master, train, checkpoint, survive restarts.

    `train_on_task(task)` is the user's callback: run the training steps
    for one leased `Task` (its `.paths` recordio shards) inside the
    scope this trainer checkpoints. Raising from it fails the lease
    (the master requeues, failure_max applies); everything else is
    handled here.
    """

    def __init__(self, master: MasterClient, ckpt_dir: str,
                 main_program=None, scope=None, registry=None,
                 checkpoint_every: int = 1, max_to_keep: int = 3,
                 poll_interval: float = 0.2, idle_timeout: float = 60.0):
        """`registry`: optional membership.WorkerRegistry — kept
        registered across the loop (a worker that lost its slot in a
        long GC pause re-claims one instead of silently vanishing from
        the elastic view). `checkpoint_every`: tasks between checkpoints
        (default 1). task_finished reports are DEFERRED to the next
        covering checkpoint, so larger values trade longer lease
        holds + more re-training after a crash for fewer checkpoint
        writes — never lost updates.
        `idle_timeout`: give up waiting for new tasks after this long
        with the queue non-empty but nothing leasable (another trainer
        holds the last leases)."""
        self._master = master
        self._ckpt_dir = ckpt_dir
        self._program = main_program
        self._scope = scope
        self._registry = registry
        self._every = max(1, int(checkpoint_every))
        self._max_to_keep = int(max_to_keep)
        self._poll = float(poll_interval)
        self._idle_timeout = float(idle_timeout)
        self.step = 0           # finished-task counter, persisted in META
        self.resumed_from: Optional[int] = None

    # -- checkpoint plumbing (fluid/io.py save/load_checkpoint) -----------
    def maybe_resume(self) -> Optional[int]:
        """Load the latest intact checkpoint if one exists; returns its
        step or None. Idempotent — run_pass calls it once up front."""
        from ..fluid.io import latest_checkpoint_step, load_checkpoint

        if self.resumed_from is not None:
            return self.resumed_from
        if latest_checkpoint_step(self._ckpt_dir) is None:
            return None
        try:
            with self._scoped():
                self.step = load_checkpoint(
                    self._ckpt_dir, self._program, scope=self._scope)
        except (IOError, OSError, ValueError, KeyError) as e:
            # a torn/corrupt payload (crc mismatch, half-written npz)
            # must NOT crash-loop every restart: training from scratch
            # is degraded, a trainer that can never start is an outage.
            # The master's leases still give the data back exactly once.
            _log.error("elastic: checkpoint in %s unusable (%s: %s); "
                       "starting fresh", self._ckpt_dir,
                       type(e).__name__, e)
            return None
        self.resumed_from = self.step
        _m_resumes.inc()
        _log.warning("elastic: resumed from checkpoint step %d in %s",
                     self.step, self._ckpt_dir)
        return self.step

    def _checkpoint(self):
        from ..fluid.io import save_checkpoint

        with self._scoped(), _tracing.span("elastic.checkpoint",
                                           step=self.step):
            save_checkpoint(self._ckpt_dir, self._program, step=self.step,
                            scope=self._scope,
                            max_to_keep=self._max_to_keep)

    def _scoped(self):
        import paddle_tpu.fluid as fluid

        if self._scope is not None:
            return fluid.scope_guard(self._scope)
        # default scope: a no-op guard keeps the call sites uniform
        import contextlib

        return contextlib.nullcontext()

    # -- the loop ---------------------------------------------------------
    def run_pass(self, train_on_task: Callable, should_stop=None
                 ) -> Dict[str, int]:
        """Drain the master's current pass: lease -> train -> checkpoint
        -> finish, until all_done. Returns summary stats. A master that
        stays unreachable past the MasterClient's retry budget aborts
        the pass gracefully (``aborted: 1`` in the stats) — the lease
        lapses server-side, exactly as if this trainer had died."""
        resumed = self.maybe_resume()
        trained = 0
        idle_since = None
        unfinished: list = []  # trained but not yet covered by a checkpoint
        while True:
            if should_stop is not None and should_stop():
                break
            if self._registry is not None:
                self._registry.ensure_registered()
            try:
                task = self._master.get_task()
            except (ConnectionError, OSError) as e:
                _log.warning("elastic: master unreachable (%s); "
                             "abandoning the pass", e)
                return {"trained": trained, "step": self.step,
                        "resumed_from": resumed, "aborted": 1}
            if task is None:
                try:
                    if self._master.all_done():
                        break
                except (ConnectionError, OSError) as e:
                    _log.warning("elastic: master unreachable (%s); "
                                 "abandoning the pass", e)
                    return {"trained": trained, "step": self.step,
                            "resumed_from": resumed, "aborted": 1}
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since > self._idle_timeout:
                    break
                time.sleep(self._poll)
                continue
            idle_since = None
            try:
                # one span per leased task: the master RPCs (finish/fail)
                # and the user's training steps nest under it, so a
                # merged timeline shows task boundaries per trainer
                with _tracing.span("elastic.task", task=task.id,
                                   epoch=task.epoch):
                    train_on_task(task)
            except Exception:
                # the task is bad or training broke: requeue with a
                # failure mark (failure_max drops poisoned shards), and
                # let the caller see the real error
                try:
                    self._master.task_failed(task.id, task.epoch)
                except (ConnectionError, OSError):
                    pass  # the lease will expire and requeue regardless
                raise
            self.step += 1
            trained += 1
            _m_tasks.inc()
            unfinished.append(task)
            if trained % self._every == 0:
                # checkpoint BEFORE finishing the leases: a crash between
                # the two re-runs those tasks on resume (at-least-once).
                # With checkpoint_every > 1 the finishes of EVERY task
                # since the last checkpoint are held back until this one
                # covers them — finishing eagerly would let a crash mark
                # tasks done whose updates no checkpoint carries, losing
                # them forever (the master never re-serves done tasks).
                self._checkpoint()
                unfinished = self._flush_finished(unfinished)
        if unfinished:
            self._checkpoint()
            self._flush_finished(unfinished)
        return {"trained": trained, "step": self.step,
                "resumed_from": resumed, "aborted": 0}

    def _flush_finished(self, tasks) -> list:
        for t in tasks:
            try:
                self._master.task_finished(t.id, t.epoch)
            except (ConnectionError, OSError) as e:
                _log.warning("elastic: task_finished(%d) unreachable "
                             "(%s); lease expiry will requeue it",
                             t.id, e)
        return []
