"""Elastic worker membership: TTL-lease registration + live-member listing
(reference go/pserver/etcd_client.go Register:70 — claim /ps/<i> with a TTL
lease and keepalive; trainers re-list to find live pservers).

TPU-era redesign on the same storage substrate as election.py: each worker
claims a numbered slot file under a registry directory with a TTL it must
keep alive; listers see exactly the live membership, dead workers' slots
expire and are reclaimed by newcomers (elastic grow/shrink). Same
filesystem requirement as election.FileLease (working POSIX locks)."""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .election import FileLease


class WorkerRegistry:
    """A directory of slot leases: /registry/slot-<i> claimed by worker id.

        reg = WorkerRegistry(dir, worker_id="trainer-3", ttl=5.0)
        slot = reg.register()       # claims the lowest free slot, heartbeats
        ...
        reg.members()               # {slot: worker_id} of LIVE workers
        reg.deregister()
    """

    def __init__(self, root: str, worker_id: str, ttl: float = 5.0,
                 max_slots: int = 1024):
        os.makedirs(root, exist_ok=True)
        self.root = root
        self.worker_id = worker_id
        # the lease holder token is unique PER PROCESS+INSTANCE: two
        # processes launched with the same worker_id (restart races,
        # misconfig) must fight for different slots, not silently share one
        # lease and evict each other (etcd leases are per-session the same
        # way). members() strips the token back to the display id.
        self._token = f"{worker_id}#{os.getpid()}-{id(self):x}"
        self.ttl = float(ttl)
        self.max_slots = max_slots
        self.slot: Optional[int] = None
        self._lease: Optional[FileLease] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _slot_path(self, i: int) -> str:
        return os.path.join(self.root, f"slot-{i:05d}")

    # -- registration (reference Register:70: loop over indices, claim the
    # first free one with a lease transaction) ---------------------------
    def register(self, timeout: float = 30.0) -> int:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for i in range(self.max_slots):
                lease = FileLease(self._slot_path(i), self._token,
                                  self.ttl)
                if lease.try_acquire():
                    self.slot = i
                    self._lease = lease
                    self._stop.clear()
                    self._thread = threading.Thread(target=self._heartbeat,
                                                    daemon=True)
                    self._thread.start()
                    return i
            time.sleep(self.ttl / 2)
        raise TimeoutError(
            f"no free worker slot in {self.root} within {timeout}s")

    def _heartbeat(self):
        while not self._stop.wait(self.ttl / 3.0):
            if self._lease is None or not self._lease.renew():
                # lost the slot (e.g. long pause past TTL): stop claiming it
                self.slot = None
                return

    def deregister(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._lease is not None:
            self._lease.release()
        self.slot = None
        self._lease = None

    def is_registered(self) -> bool:
        return (self.slot is not None and self._lease is not None
                and self._lease.current().get("holder") == self._token)

    def ensure_registered(self, timeout: float = 30.0) -> int:
        """Re-claim a slot if ours lapsed (a GC pause past the TTL makes
        the heartbeat thread give the slot up — see _heartbeat). Elastic
        loops call this each iteration so a worker that is actually
        alive never stays invisible to the membership view. No-op when
        the current lease is healthy."""
        if self.is_registered():
            return self.slot
        if self._lease is not None:  # stale thread/lease: tear down first
            self.deregister()
        return self.register(timeout=timeout)

    # -- listing ----------------------------------------------------------
    def members(self) -> Dict[int, str]:
        """Live workers only: expired leases are invisible (the elastic
        membership view trainers/pservers re-resolve from)."""
        out: Dict[int, str] = {}
        now = time.time()
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.startswith("slot-") or name.endswith(".lock") \
                    or name.endswith(".tmp"):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    st = json.load(f)
            except (OSError, ValueError):
                continue
            if st.get("holder") and st.get("deadline", 0) > now:
                out[int(name.split("-")[1])] = st["holder"].split("#")[0]
        return out

    def wait_for(self, n: int, timeout: float = 60.0) -> List[str]:
        """Block until >= n live members (the reference's barrier before
        FinishInitParams)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            m = self.members()
            if len(m) >= n:
                return [m[k] for k in sorted(m)]
            time.sleep(0.1)
        raise TimeoutError(f"only {len(self.members())}/{n} workers live")
