"""Distributed layer: multi-host initialization, elastic data service,
distribute-transpiler facade.

TPU-native replacement for the reference's distributed stack (SURVEY.md
§2.10): gRPC pserver ops + NCCL handles + Go master/pserver become
  - `init_distributed` — jax.distributed over DCN (coordinator + N hosts),
    after which jax.devices() spans all hosts and the same pjit program is
    data/model-parallel across them (collectives ride ICI within a slice,
    DCN across slices),
  - `MasterService`/`MasterClient` — go/master-parity elastic task queue
    over recordio shards with lease timeouts, failure counts and snapshot
    recovery (file-based instead of etcd),
  - `ElectedMaster`/`FileLease`/`endpoint_resolver` — leader election with
    standby takeover from the shared snapshot and client endpoint
    re-resolution (role of go/master/etcd_client.go's campaign +
    go/pserver/etcd_client.go's TTL-lease registration),
  - `fluid.DistributeTranspiler` — API-parity facade mapping the pserver
    program-rewrite world onto mesh+sharding-plan SPMD,
  - `ElasticTrainer` — checkpoint-resume task loop (kill a trainer,
    restart it, training continues from the last intact checkpoint),
  - `faults` — deterministic fault injection (PADDLE_TPU_FAULTS) that
    the RPC layer and master consult; docs/FAULT_TOLERANCE.md covers
    the spec grammar and the retry/idempotency/eviction semantics.
"""
from . import faults  # noqa: F401
from .elastic import ElasticTrainer  # noqa: F401
from .election import (  # noqa: F401
    ElectedMaster,
    FileLease,
    endpoint_resolver,
)
from .env import get_world_info, global_mesh, init_distributed  # noqa: F401
from .master import MasterClient, MasterService  # noqa: F401
from .membership import WorkerRegistry  # noqa: F401
