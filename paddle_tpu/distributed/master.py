"""Elastic master data service: fault-tolerant task queue over dataset
shards.

Capability parity with the reference's Go master (go/master/service.go:
Service:89 todo/pending/done/failed queues, partition:106, GetTask:368
leased with timeout, checkTimeoutFunc:341, TaskFinished:411,
TaskFailed:455 re-queue with failureMax drop, snapshot:207/recover:166 via
etcd). TPU-era redesign: the queue state snapshots to a local file (crc32 +
atomic rename — the same integrity trick as the Go pserver checkpoints,
go/pserver/service.go:53); service runs in-process or over a TCP
pickle-RPC for multi-trainer jobs. Tasks are recordio shard path groups,
exactly like the reference partitions chunks.
"""
from __future__ import annotations

import json
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from . import faults as _faults
from ..observability import tracing as _tracing
from ..observability.log import get_logger

_log = get_logger("master")

# v2 snapshot magic (see _snapshot_locked for the layout); files without
# it are the legacy crc|payload format (term 0)
_SNAP_MAGIC = b"PTSNAP2\x00"


class MasterDeposed(Exception):
    """This master no longer holds the leadership lease: mutating RPCs and
    snapshot writes must not proceed (fencing)."""


@dataclass
class Task:
    id: int
    paths: List[str]
    num_failures: int = 0
    epoch: int = 0  # lease generation; stale finish/fail calls are rejected


def _to_wire(v):
    """RPC result -> JSON-safe value (Task gets a type tag)."""
    if isinstance(v, Task):
        return {"__task__": {"id": v.id, "paths": list(v.paths),
                             "num_failures": v.num_failures,
                             "epoch": v.epoch}}
    return v


def _from_wire(v):
    if isinstance(v, dict) and "__task__" in v:
        t = v["__task__"]
        return Task(id=t["id"], paths=list(t["paths"]),
                    num_failures=t["num_failures"], epoch=t["epoch"])
    return v


@dataclass
class _Pending:
    task: Task
    epoch: int
    deadline: float


class MasterService:
    """Task queue with leases. Thread-safe; optionally snapshot-backed."""

    def __init__(self, chunks_per_task: int = 1, lease_timeout: float = 60.0,
                 failure_max: int = 3, snapshot_path: Optional[str] = None,
                 snapshot_fence=None, snapshot_term: int = 0):
        self._chunks_per_task = chunks_per_task
        self._timeout = lease_timeout
        self._failure_max = failure_max
        self._snapshot_path = snapshot_path
        # fence(commit): run `commit` only while leadership is still held,
        # else raise MasterDeposed — prevents a stale leader overwriting
        # the new leader's snapshot (election.FileLease.fenced)
        self._snapshot_fence = snapshot_fence
        # monotonic fencing term stamped into every snapshot this service
        # writes (the lease term under which it was elected). The commit
        # refuses to replace a snapshot carrying a HIGHER term, so a
        # deposed leader that slipped past a check-then-commit fence
        # (tcp_lease.TcpLease cannot hold the server mutex across the
        # client-side rename the way FileLease holds flock) still cannot
        # roll the new leader's state back. 0 = unelected/standalone use.
        self._snapshot_term = int(snapshot_term)
        self._mu = threading.Lock()
        self._todo: List[Task] = []
        self._pending: Dict[int, _Pending] = {}
        self._done: List[Task] = []
        self._failed_dropped: List[Task] = []
        self._epoch = 0
        self._next_id = 0
        self._dataset_paths: Optional[List[str]] = None
        self._cur_pass = 0
        self._sweep_stop: Optional[threading.Event] = None
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- lease sweeper -----------------------------------------------------
    def start_timeout_sweeper(self, interval: Optional[float] = None):
        """Expire leases on a TIMER, not only piggybacked on other calls:
        _check_timeouts_locked used to fire solely inside get_task/
        all_done/new_pass, so with no client polling (every trainer dead
        or wedged) a lapsed lease stayed pending forever. Off by default
        for in-process use; serve() turns it on. Idempotent; stopped by
        shutdown()/stop_timeout_sweeper()."""
        if self._sweep_stop is not None:
            return
        stop = self._sweep_stop = threading.Event()
        interval = interval if interval is not None else \
            max(0.05, self._timeout / 3.0)

        def _sweep():
            while not stop.wait(interval):
                try:
                    with self._mu:
                        self._check_timeouts_locked()
                except MasterDeposed:
                    # a deposed leader must stop mutating state — and
                    # must not leave the stale stop-event wedging a
                    # future start_timeout_sweeper after re-election
                    if self._sweep_stop is stop:
                        self._sweep_stop = None
                    return
                except Exception as e:  # never die silently mid-job
                    _log.error("lease sweeper: %s: %s",
                               type(e).__name__, e)

        t = threading.Thread(target=_sweep, daemon=True,
                             name="master-lease-sweeper")
        t.start()

    def stop_timeout_sweeper(self):
        if self._sweep_stop is not None:
            self._sweep_stop.set()
            self._sweep_stop = None

    # -- dataset ----------------------------------------------------------
    def set_dataset(self, shard_paths: Sequence[str]):
        """Partition shards into tasks (reference partition:106).
        IDEMPOTENT for an unchanged shard list: a second worker joining
        the fleet must drain the EXISTING queues, not reset them (a reset
        would invalidate in-flight leases and re-serve finished tasks)."""
        with self._mu:
            if list(shard_paths) == self._dataset_paths:
                return
            self._dataset_paths = list(shard_paths)
            self._todo = []
            self._pending.clear()
            self._done = []
            self._failed_dropped = []
            cur: List[str] = []
            for p in shard_paths:
                cur.append(p)
                if len(cur) >= self._chunks_per_task:
                    self._todo.append(Task(self._next_id, cur))
                    self._next_id += 1
                    cur = []
            if cur:
                self._todo.append(Task(self._next_id, cur))
                self._next_id += 1
            self._snapshot_locked()

    # -- task protocol ----------------------------------------------------
    def get_task(self) -> Optional[Task]:
        """Lease a task; None when nothing is available right now (reference
        GetTask:368). Re-queues timed-out leases first."""
        with self._mu:
            self._check_timeouts_locked()
            if not self._todo:
                return None
            task = self._todo.pop(0)
            self._epoch += 1
            task.epoch = self._epoch
            self._pending[task.id] = _Pending(
                task, self._epoch, time.monotonic() + self._timeout
            )
            self._snapshot_locked()
            # hand out a copy: in-process clients must not alias the queue's
            # mutable task (its epoch advances on re-lease)
            import dataclasses as _dc

            return _dc.replace(task, paths=list(task.paths))

    def _pop_pending(self, task_id: int, epoch: Optional[int]):
        """A stale lease holder (its lease timed out and the task was
        re-leased) must not affect the new holder's lease — the epoch check
        (reference go/master keeps per-lease epochs for exactly this)."""
        p = self._pending.get(task_id)
        if p is None or (epoch is not None and p.epoch != epoch):
            return None
        return self._pending.pop(task_id)

    def task_finished(self, task_id: int, epoch: Optional[int] = None) -> bool:
        """reference TaskFinished:411."""
        with self._mu:
            p = self._pop_pending(task_id, epoch)
            if p is None:
                return False
            self._done.append(p.task)
            self._snapshot_locked()
            return True

    def task_failed(self, task_id: int, epoch: Optional[int] = None) -> bool:
        """Requeue; drop after failure_max (reference TaskFailed:455,
        :313-339)."""
        with self._mu:
            p = self._pop_pending(task_id, epoch)
            if p is None:
                return False
            self._fail_locked(p.task)
            self._snapshot_locked()
            return True

    def task_released(self, task_id: int, epoch: Optional[int] = None) -> bool:
        """Voluntary lease release (client abandons a pass mid-task):
        requeue IMMEDIATELY and WITHOUT a failure mark — unlike
        task_failed, releasing is not evidence the task is bad, so it must
        not count toward failure_max's drop threshold."""
        with self._mu:
            p = self._pop_pending(task_id, epoch)
            if p is None:
                return False
            self._todo.append(p.task)
            self._snapshot_locked()
            return True

    def all_done(self) -> bool:
        with self._mu:
            self._check_timeouts_locked()
            return not self._todo and not self._pending

    def new_pass(self) -> bool:
        """Start the next pass when the current one is exhausted: done
        (and dropped) tasks re-queue as todo (reference TaskFinished's
        rollover, service.go:435-445 — made EXPLICIT here because this
        service's clients detect pass end via all_done(), which an
        automatic rollover would never let become true). Returns False
        while tasks are still outstanding."""
        with self._mu:
            self._check_timeouts_locked()
            if self._todo or self._pending:
                return False
            if not self._done and not self._failed_dropped:
                return False
            self._cur_pass += 1
            self._todo = self._done + self._failed_dropped
            self._done = []
            self._failed_dropped = []
            for t in self._todo:
                t.num_failures = 0
            self._snapshot_locked()
            return True

    def stats(self) -> Dict[str, int]:
        with self._mu:
            return {
                "todo": len(self._todo), "pending": len(self._pending),
                "done": len(self._done),
                "dropped": len(self._failed_dropped),
                "pass": self._cur_pass,
            }

    def _fail_locked(self, task: Task):
        task.num_failures += 1
        if task.num_failures >= self._failure_max:
            self._failed_dropped.append(task)
        else:
            self._todo.append(task)

    def _check_timeouts_locked(self):
        now = time.monotonic()
        expired = [tid for tid, p in self._pending.items()
                   if p.deadline <= now]
        for tid in expired:
            p = self._pending.pop(tid)
            self._fail_locked(p.task)
        if expired:
            self._snapshot_locked()

    # -- snapshot / recover (reference snapshot:207, recover:166) ---------
    def _snapshot_locked(self):
        if not self._snapshot_path:
            return
        state = {
            "todo": self._todo,
            # pending leases survive as todo on recovery (the lease holder
            # may be the one that died)
            "pending": [p.task for p in self._pending.values()],
            "done": self._done,
            "dropped": self._failed_dropped,
            "next_id": self._next_id,
            # epoch must survive recovery or pre-crash stale leases could
            # collide with fresh ones and defeat the epoch guard
            "epoch": self._epoch,
            # the set_dataset idempotency guard keys on these: without them
            # a recovered master treats the first (unchanged) set_dataset as
            # new, resets the queues, and re-serves finished tasks
            "dataset_paths": self._dataset_paths,
            "pass": self._cur_pass,
        }
        payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        # v2 format: magic | term u64 | crc32(term) | crc32(payload) |
        # payload. The term lives in a fixed-size, separately-checksummed
        # header so the monotonic-write guard reads the 24-byte header — not the
        # whole queue state — per commit, and a torn header can't fake a
        # high term and wedge commits. Legacy (magic-less crc|payload)
        # snapshots still recover, with term 0.
        term8 = struct.pack("<Q", self._snapshot_term)
        blob = (_SNAP_MAGIC + term8
                + struct.pack("<II", zlib.crc32(term8), zlib.crc32(payload))
                + payload)
        # per-process unique tmp: on shared storage a deposed leader writing
        # a FIXED tmp path could corrupt the new leader's in-flight commit
        # (the fence only guards the rename)
        tmp = f"{self._snapshot_path}.tmp.{os.getpid()}.{id(self):x}"
        with open(tmp, "wb") as f:
            f.write(blob)

        def _commit():
            # chaos hook: a crash HERE (tmp written, rename not yet done)
            # is the classic torn-checkpoint window — recovery must see
            # the intact previous snapshot, never the tmp
            _faults.fire("master.snapshot")
            # Monotonic-term guard: never replace a snapshot written under
            # a NEWER leadership term. FileLease.fenced holds flock across
            # this rename, closing the race completely; TcpLease.fenced is
            # check-then-commit (the lease server cannot extend its mutex
            # over a client-side rename), so a leader that stalls between
            # check and commit could otherwise clobber its successor's
            # state. With the term check, the stale rename is refused the
            # moment the successor (higher term) has committed once — the
            # residual window shrinks from the stall length to the
            # read-compare-rename microseconds, and a write that does slip
            # through is corrected by the successor's next snapshot (task
            # leases it re-serves simply time out and requeue: the
            # at-least-once semantics the queue already guarantees).
            cur = self._read_snapshot_term()
            if cur is not None and cur > self._snapshot_term:
                raise MasterDeposed(
                    f"snapshot already at term {cur} > ours "
                    f"{self._snapshot_term}: refusing stale write")
            os.replace(tmp, self._snapshot_path)

        try:
            if self._snapshot_fence is not None:
                self._snapshot_fence(_commit)  # raises MasterDeposed if stale
            else:
                _commit()
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def _read_snapshot_term(self) -> Optional[int]:
        """Term of the current on-disk snapshot (24-byte header read, not
        the whole state), or None if there is no readable/intact header.
        Only an INTEGRITY-CHECKED term counts: a torn header must not be
        able to fake a high term and wedge commits forever. Legacy
        (pre-term) snapshots read as term 0."""
        try:
            with open(self._snapshot_path, "rb") as f:
                head = f.read(len(_SNAP_MAGIC) + 16)
        except OSError:
            return None
        if not head.startswith(_SNAP_MAGIC):
            return 0  # legacy crc|payload format carried no term
        m = len(_SNAP_MAGIC)
        if len(head) < m + 16:
            return None
        term8 = head[m:m + 8]
        (crc_t, _crc_p) = struct.unpack("<II", head[m + 8:m + 16])
        if zlib.crc32(term8) != crc_t:
            return None
        return struct.unpack("<Q", term8)[0]

    def _recover(self):
        with open(self._snapshot_path, "rb") as f:
            blob = f.read()
        if blob.startswith(_SNAP_MAGIC):
            m = len(_SNAP_MAGIC)
            term8 = blob[m:m + 8]
            (crc_t, crc_p) = struct.unpack("<II", blob[m + 8:m + 16])
            payload = blob[m + 16:]
            if zlib.crc32(term8) != crc_t or zlib.crc32(payload) != crc_p:
                raise IOError(f"{self._snapshot_path}: snapshot corrupt")
            recovered_term = struct.unpack("<Q", term8)[0]
        else:
            # legacy format: crc32(payload) | payload, no term
            (crc,) = struct.unpack("<I", blob[:4])
            payload = blob[4:]
            if zlib.crc32(payload) != crc:
                raise IOError(f"{self._snapshot_path}: snapshot corrupt")
            recovered_term = 0
        # Adopt the recovered term when it is higher than ours: a
        # standalone service (term 0) or a leader elected from a
        # RESTARTED lease server (terms reset to 1) must be able to keep
        # committing over a higher-term snapshot rather than raising
        # MasterDeposed on every mutation forever. The cost is that
        # fencing between two post-restart leaders degrades to the
        # check-fence until lease terms catch up — persistence on the
        # LeaseServer side (state_path) avoids the reset entirely.
        self._snapshot_term = max(self._snapshot_term, recovered_term)
        state = pickle.loads(payload)
        self._todo = state["todo"] + state["pending"]
        self._done = state["done"]
        self._failed_dropped = state["dropped"]
        self._next_id = state["next_id"]
        self._epoch = state.get("epoch", 0)
        if state.get("dataset_paths") is not None:
            self._dataset_paths = state["dataset_paths"]
        self._cur_pass = state.get("pass", 0)

    # -- TCP server (role of the reference's net/rpc endpoint) ------------
    # RPC surface exposed over TCP — everything else is unreachable
    _RPC_METHODS = frozenset({
        "set_dataset", "get_task", "task_finished", "task_failed",
        "task_released", "all_done", "new_pass", "stats",
    })

    # frames larger than this are a protocol violation (a real set_dataset
    # of ~100k shard paths is well under 8 MiB); caps the per-connection
    # allocation a hostile peer can force
    _MAX_FRAME = 8 << 20

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        """Start serving in a daemon thread; returns (host, port).

        Frames are length-prefixed JSON (size-capped) — every RPC
        argument/result is paths/ints/bools/Task, so nothing needs pickle,
        and a hostile peer can at worst get a parse error or a dropped
        connection (the reference's in-cluster RPC is protobuf for the same
        reason)."""
        service = self
        self._conns = set()
        self._conns_mu = threading.Lock()

        from .rpc import read_frame, write_frame

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                with service._conns_mu:
                    service._conns.add(self.connection)
                try:
                    while True:
                        try:
                            req = read_frame(
                                self.rfile,
                                max_frame=MasterService._MAX_FRAME)
                        except json.JSONDecodeError as e:
                            # malformed but well-framed: report + keep serving
                            write_frame(self.wfile,
                                        {"ok": False,
                                         "error": f"bad frame: {e}"})
                            continue
                        except IOError:
                            return  # protocol violation: drop the peer
                        if req is None:
                            return
                        # trace-context propagation (ISSUE 3): same
                        # adopt-and-answer protocol as distributed/rpc.py
                        # — the master's frames are plain JSON, so the
                        # header rides as a request key
                        wire_tr = req.pop("__trace__", None) \
                            if isinstance(req, dict) else None
                        try:
                            method = req["method"]
                            if method not in MasterService._RPC_METHODS:
                                raise ValueError(
                                    f"unknown RPC method {method!r}")
                            with _tracing.adopt(wire_tr), \
                                    _tracing.span(f"master.{method}",
                                                  method=method):
                                if wire_tr:
                                    _tracing.flow_end(wire_tr.get("f"))
                                result = getattr(service, method)(
                                    *req["args"])
                            resp = {"ok": True, "result": _to_wire(result)}
                        except MasterDeposed:
                            # this master lost its lease mid-call: sever the
                            # connection so the client re-resolves to the
                            # new leader instead of getting app errors
                            return
                        except Exception as e:  # report, keep serving
                            resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                        write_frame(self.wfile, resp)
                except (ConnectionError, EOFError):
                    return
                finally:
                    with service._conns_mu:
                        service._conns.discard(self.connection)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        # a SERVED master owns lease expiry itself: remote clients may all
        # be dead, and dead clients are exactly when expiry matters
        self.start_timeout_sweeper()
        addr = self._server.server_address
        if _tracing.process_label() is None:
            _tracing.set_process_label(f"master:{addr[1]}")
        # live introspection (ISSUE 3): PADDLE_TPU_DEBUG_PORT attaches
        # the process-shared debug HTTP server and registers this
        # service's queue state under /statusz
        from ..observability import debug_server as _dbg

        self._debug_key = f"master:{addr[1]}"
        if _dbg.maybe_serve_from_env() is not None:
            _dbg.add_status(self._debug_key, self._debug_status)
        return addr

    def _debug_status(self):
        """Queue-state view for /statusz (never blocks long: stats()
        takes the service lock briefly)."""
        return {
            "stats": self.stats(),
            "lease_timeout_s": self._timeout,
            "failure_max": self._failure_max,
            "snapshot_path": self._snapshot_path,
            "snapshot_term": self._snapshot_term,
            "sweeper_running": self._sweep_stop is not None,
        }

    def shutdown(self):
        """Stop the listener AND sever established connections — a deposed
        leader must not keep serving clients that still hold open sockets
        (they would never re-resolve to the new leader: split-brain)."""
        from ..observability import debug_server as _dbg

        _dbg.remove_status(getattr(self, "_debug_key", None))
        self.stop_timeout_sweeper()
        srv = getattr(self, "_server", None)
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        with getattr(self, "_conns_mu", threading.Lock()):
            for conn in list(getattr(self, "_conns", ())):
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            if hasattr(self, "_conns"):
                self._conns.clear()


class MasterClient:
    """Trainer-side client (reference go/master/client.go + the ctypes
    python/paddle/v2/master/client.py). Also usable in-process by passing
    the service itself."""

    def __init__(self, addr=None, service: Optional[MasterService] = None,
                 addr_resolver=None, reconnect_retries: int = 8,
                 reconnect_backoff: float = 0.2,
                 timeout: Optional[float] = None):
        """`addr_resolver`: zero-arg callable returning (host, port) of the
        CURRENT master (see election.endpoint_resolver) — consulted on every
        (re)connect, so a standby takeover is followed automatically.
        Retries with backoff span the election gap after a master crash.
        `timeout`: dial + per-RPC deadline in seconds (None = block forever)
        — the role of the reference ctypes client's timeout_sec."""
        self._service = service
        self._timeout = timeout
        if isinstance(addr, str):  # "host:port" accepted everywhere
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self._addr = addr
        self._resolver = addr_resolver
        self._retries = int(reconnect_retries)
        self._backoff = float(reconnect_backoff)
        self._sock = None  # guarded-by: _lock
        self._rfile = None  # guarded-by: _lock
        self._wfile = None  # guarded-by: _lock
        self._lock = threading.Lock()

    def _call(self, method: str, *args):
        if self._service is not None:
            return getattr(self._service, method)(*args)
        last_err: Optional[Exception] = None
        for attempt in range(self._retries + 1):
            try:
                return self._call_once(method, *args)
            except (ConnectionError, OSError) as e:
                # master died or a standby is taking over: back off, then
                # re-resolve the endpoint and retry (get_task/task_finished/
                # task_failed are safe to retry — the lease epoch guard
                # rejects stale duplicates)
                last_err = e
                if attempt < self._retries:
                    time.sleep(self._backoff * (attempt + 1))
        raise ConnectionError(
            f"master unreachable after {self._retries + 1} attempts: "
            f"{last_err}") from last_err

    def _call_once(self, method: str, *args):
        from .rpc import read_frame, write_frame

        # lint: allow-blocking — _lock serializes this client's frames on
        # its single master connection (same design as RpcClient.call);
        # concurrent trainers each hold their own MasterClient.
        with self._lock, _tracing.span(f"master.client.{method}",
                                       method=method):
            req = {"method": method, "args": list(args)}
            if _tracing.trace_enabled():
                fid = _tracing.new_flow_id()
                wire_tr = _tracing.wire_context(fid)
                if wire_tr is not None:
                    req["__trace__"] = wire_tr
                    _tracing.flow_start(fid)
            try:
                if self._sock is None:
                    addr = self._resolver() if self._resolver else self._addr
                    # timeout covers the dial AND every subsequent
                    # read/write on the socket (a wedged master surfaces
                    # as socket.timeout -> OSError -> retry/raise, not a
                    # silent hang)
                    self._sock = socket.create_connection(
                        addr, timeout=self._timeout)
                    self._rfile = self._sock.makefile("rb")
                    self._wfile = self._sock.makefile("wb")
                # sender-side cap must match the SERVER's read cap, or an
                # oversized request dies as an opaque dropped connection
                write_frame(self._wfile, req,
                            max_frame=MasterService._MAX_FRAME)
                resp = read_frame(self._rfile)
                if resp is None:
                    raise ConnectionError(
                        "master closed the connection mid-call")
            except (ConnectionError, OSError):
                # drop the broken socket so the next call reconnects
                try:
                    if self._sock is not None:
                        self._sock.close()
                finally:
                    self._sock = None
                raise
            if not resp.get("ok"):
                raise RuntimeError(f"master RPC failed: {resp.get('error')}")
            return _from_wire(resp.get("result"))

    def set_dataset(self, shard_paths: Sequence[str]):
        return self._call("set_dataset", list(shard_paths))

    def get_task(self) -> Optional[Task]:
        return self._call("get_task")

    def task_finished(self, task_id: int, epoch: Optional[int] = None) -> bool:
        return self._call("task_finished", task_id, epoch)

    def task_failed(self, task_id: int, epoch: Optional[int] = None) -> bool:
        return self._call("task_failed", task_id, epoch)

    def task_released(self, task_id: int, epoch: Optional[int] = None) -> bool:
        """Voluntarily return a leased task to todo, without failure mark."""
        return self._call("task_released", task_id, epoch)

    def all_done(self) -> bool:
        return self._call("all_done")

    def new_pass(self) -> bool:
        """Re-queue the finished pass's tasks for another epoch."""
        return self._call("new_pass")

    def stats(self):
        return self._call("stats")

    def records(self, poll_interval: float = 0.2, should_stop=None):
        """Iterate every record of the leased tasks until the dataset is
        exhausted (role of client.go NextRecord): lease task -> stream its
        recordio shards -> mark finished; crashes mid-task just let the
        lease expire and another trainer re-reads it. `should_stop`:
        zero-arg callable polled while WAITING for a task — lets a
        prefetch pump abandon the pass even when it is parked in the poll
        loop (another trainer holding the last lease), not just at a
        yield."""
        from ..native.recordio import multi_file_reader

        while True:
            if should_stop is not None and should_stop():
                return
            task = self.get_task()
            if task is None:
                if self.all_done():
                    return
                time.sleep(poll_interval)
                continue
            try:
                for rec in multi_file_reader(task.paths):
                    yield rec
            except GeneratorExit:
                # consumer abandoned the pass (gen.close()): hand the
                # lease back NOW so the task re-serves immediately instead
                # of after lease_timeout — and without a failure mark.
                # An unreachable master amounts to the same thing: the
                # lease expires and the task re-serves.
                try:
                    self.task_released(task.id, task.epoch)
                except (ConnectionError, OSError):
                    pass
                raise
            except Exception:
                try:
                    self.task_failed(task.id, task.epoch)
                except (ConnectionError, OSError):
                    # can't report the failure: the lease will expire and
                    # requeue the task anyway — surface the ORIGINAL error
                    _log.warning("task_failed(%d) unreachable; letting "
                                 "the lease expire", task.id)
                raise
            try:
                self.task_finished(task.id, task.epoch)
            except (ConnectionError, OSError) as e:
                # RPC failure is NOT a trainer crash: the master (or its
                # successor) re-serves this task when the lease lapses —
                # at-least-once delivery, same as a death mid-task. Keep
                # training on the next lease instead of dying here.
                _log.warning("task_finished(%d) unreachable (%s); task "
                             "re-serves via lease expiry", task.id, e)

    def close(self):
        # under _lock (guards-lint finding): a close() racing another
        # thread's in-flight _call_once could tear the socket down
        # mid-frame — or leak the one create_connection just opened
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
