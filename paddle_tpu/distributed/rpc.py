"""Length-prefixed JSON RPC framing — the transport under the parameter
server (and the same wire shape the master service uses,
distributed/master.py:serve). One message = 4-byte little-endian length +
UTF-8 JSON header, then zero or more RAW binary segments (lengths listed
in the header's "__segs__"). Tensors ride as raw segments — no base64
inflation, no JSON number lists — matching the reference transport's
zero-copy intent (operators/detail/sendrecvop_utils.cc serializes
VariableMessage as name + type + dims + chunked raw bytes; the proto is
send_recv.proto:17). Small/legacy frames may still inline tensors as
base64 blobs; both decode. Nothing needs pickle, so a hostile peer can at
worst force a parse error or a dropped connection.
"""
from __future__ import annotations

import base64
import json
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger

_log = get_logger("rpc")

# the JSON header is small once tensors ride as segments: 16 MiB is roomy
MAX_FRAME = 16 << 20
# raw tensor segments per message: 1 GiB total
MAX_SEGMENT_BYTES = 1 << 30


class _ByteMeter(threading.local):
    """Per-thread wire-byte tally. read_frame/write_frame credit it as
    bytes cross the socket; RpcClient.call and the server handler
    snapshot it around each message to attribute deltas to their side's
    counters. Thread-local so concurrent client threads and server
    handler threads never share (or contend on) an accumulator."""

    def __init__(self):
        self.read = 0
        self.written = 0


_meter = _ByteMeter()

# client-side observability: per-method latency histograms are created on
# first use (method sets are small); byte/retry/timeout counters are flat
_m_cli_bytes_out = _metrics.counter("rpc.client.bytes_out")
_m_cli_bytes_in = _metrics.counter("rpc.client.bytes_in")
_m_cli_retries = _metrics.counter("rpc.client.connect_retries")
_m_cli_timeouts = _metrics.counter("rpc.client.timeouts")
_m_cli_errors = _metrics.counter("rpc.client.errors")
_m_srv_bytes_out = _metrics.counter("rpc.server.bytes_out")
_m_srv_bytes_in = _metrics.counter("rpc.server.bytes_in")
_m_srv_errors = _metrics.counter("rpc.server.errors")


def to_wire(obj, segs: Optional[list] = None):
    """JSON-encode numpy arrays and SelectedRows. With `segs` (a list to
    append to), tensor bytes become out-of-band raw segments referenced by
    index; without it they inline as base64 (legacy/small-frame form)."""
    from ..fluid.selected_rows import SelectedRows, is_selected_rows

    if is_selected_rows(obj):
        return {"__sr__": {
            "rows": to_wire(np.asarray(obj.rows), segs),
            "value": to_wire(np.asarray(obj.value), segs),
            "height": int(obj.height),
        }}
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        spec = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
        if segs is not None:
            spec["seg"] = len(segs)
            segs.append(arr.tobytes())
        else:
            spec["b64"] = base64.b64encode(arr.tobytes()).decode("ascii")
        return {"__nd__": spec}
    if isinstance(obj, dict):
        return {k: to_wire(v, segs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v, segs) for v in obj]
    return obj


def from_wire(obj, segs: Optional[list] = None):
    from ..fluid.selected_rows import SelectedRows

    if isinstance(obj, dict):
        if "__nd__" in obj and len(obj) == 1:
            spec = obj["__nd__"]
            if "seg" in spec:
                if segs is None:
                    raise ValueError("segment-encoded tensor in a message "
                                     "read without segments")
                raw = segs[int(spec["seg"])]
            else:
                raw = base64.b64decode(spec["b64"])
            arr = np.frombuffer(
                raw, dtype=np.dtype(spec["dtype"])
            ).reshape(spec["shape"])
            return arr.copy()  # writable, owns its memory
        if "__sr__" in obj and len(obj) == 1:
            spec = obj["__sr__"]
            return SelectedRows(
                from_wire(spec["rows"], segs), from_wire(spec["value"], segs),
                int(spec["height"]),
            )
        return {k: from_wire(v, segs) for k, v in obj.items()}
    if isinstance(obj, list):
        return [from_wire(v, segs) for v in obj]
    return obj


def read_frame(rfile, max_frame: int = MAX_FRAME) -> Optional[dict]:
    head = rfile.read(4)
    if len(head) != 4:
        return None
    (n,) = struct.unpack("<I", head)
    if n > max_frame:
        raise IOError(f"frame of {n} bytes exceeds cap")
    body = rfile.read(n)
    if len(body) != n:
        return None
    _meter.read += 4 + n
    return json.loads(body.decode("utf-8"))


def write_frame(wfile, obj: dict, max_frame: int = MAX_FRAME):
    out = json.dumps(obj).encode("utf-8")
    if len(out) > max_frame:
        # fail HERE with the cause — the receiver would just drop the
        # connection, and the sender would retry the same oversized
        # payload forever behind an opaque ConnectionError
        raise IOError(
            f"frame of {len(out)} bytes exceeds the {max_frame}-byte cap "
            "(tensor too large for one RPC — shard it)"
        )
    wfile.write(struct.pack("<I", len(out)) + out)
    wfile.flush()
    _meter.written += 4 + len(out)


def write_msg(wfile, obj, max_frame: int = MAX_FRAME):
    """Encode `obj` (tensors as raw segments) and write header + segments.
    All size checks happen BEFORE the first byte hits the socket, so an
    oversized payload raises IOError with the stream still clean — the
    caller can still send a small error frame on the same connection."""
    segs: list = []
    wire = to_wire(obj, segs)
    total = sum(len(s) for s in segs)
    if total > MAX_SEGMENT_BYTES:
        raise IOError(
            f"message tensors total {total} bytes, exceeding the "
            f"{MAX_SEGMENT_BYTES}-byte cap (shard the tensor)"
        )
    if segs:
        wire = {"__segs__": [len(s) for s in segs], **wire} \
            if isinstance(wire, dict) else {"__segs__": [len(s) for s in segs],
                                            "__body__": wire}
    write_frame(wfile, wire, max_frame)
    for s in segs:
        wfile.write(s)
        _meter.written += len(s)
    if segs:
        wfile.flush()


def read_msg(rfile, max_frame: int = MAX_FRAME):
    """Read one header frame + its raw segments. Returns (obj, segs) with
    tensors NOT yet decoded — pass both to from_wire — or None on EOF."""
    obj = read_frame(rfile, max_frame)
    if obj is None:
        return None
    segs: list = []
    if isinstance(obj, dict) and "__segs__" in obj:
        lens = obj.pop("__segs__")
        # validate EVERY length individually: a negative entry would turn
        # rfile.read(-1) into a read-until-EOF hang, and mixed
        # negative/huge entries could cancel out in a sum-only check
        total = 0
        for n in lens:
            n = int(n)
            if n < 0 or n > MAX_SEGMENT_BYTES:
                raise IOError(f"bad segment length {n}")
            total += n
            if total > MAX_SEGMENT_BYTES:
                raise IOError("declared segments exceed the byte cap")
        for n in lens:
            b = rfile.read(int(n))
            if len(b) != int(n):
                return None
            _meter.read += len(b)
            segs.append(b)
        if "__body__" in obj and len(obj) == 1:
            obj = obj["__body__"]
    return obj, segs


class RpcServer:
    """Threaded JSON-RPC server over a method dispatch table."""

    def __init__(self, methods: Dict[str, Callable]):
        self._methods = dict(methods)
        self._server: Optional[socketserver.ThreadingTCPServer] = None

    def serve(self, host: str = "127.0.0.1", port: int = 0
              ) -> Tuple[str, int]:
        methods = self._methods

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    while True:
                        r0, w0 = _meter.read, _meter.written
                        try:
                            msg = read_msg(self.rfile)
                        except (json.JSONDecodeError, UnicodeDecodeError) as e:
                            # malformed header: report, then CLOSE. If the
                            # unparseable header declared __segs__, their raw
                            # bytes are still on the wire and cannot be
                            # skipped — reading on would parse tensor bytes
                            # as the next length prefix and silently desync
                            _m_srv_errors.inc()
                            _log.error(
                                "bad frame from %s: %s",
                                self.client_address, e)
                            write_frame(self.wfile,
                                        {"ok": False,
                                         "error": f"bad frame: {e}"})
                            return
                        if msg is None:
                            return
                        req, segs = msg
                        method = req.get("method", "?")
                        t0 = time.perf_counter()
                        with _tracing.span("rpc.server.handle",
                                           method=method):
                            try:
                                fn = methods.get(method)
                                if fn is None:
                                    raise ValueError(
                                        f"unknown RPC method {method!r}")
                                result = fn(
                                    *from_wire(req.get("args", []), segs))
                                resp = {"ok": True, "result": result}
                            except Exception as e:  # report, keep serving
                                # handler failures used to surface only
                                # client-side; name the method and peer so
                                # the server's log carries the evidence
                                _m_srv_errors.inc()
                                _log.error(
                                    "handler %r failed for peer %s: "
                                    "%s: %s", method, self.client_address,
                                    type(e).__name__, e)
                                resp = {"ok": False,
                                        "error": f"{type(e).__name__}: {e}"}
                        if method in methods:
                            # per-method only for REGISTERED methods — a
                            # hostile peer must not mint unbounded metric
                            # names into the process-wide registry
                            _metrics.histogram(
                                f"rpc.server.{method}.ms").observe(
                                    (time.perf_counter() - t0) * 1e3)
                        try:
                            write_msg(self.wfile, resp)
                        except IOError as e:
                            # oversized response (caught before any byte was
                            # written): tell the CLIENT why instead of
                            # dropping the connection into an opaque
                            # "server closed mid-call"
                            _m_srv_errors.inc()
                            _log.error(
                                "oversized response to %r for peer %s: %s",
                                method, self.client_address, e)
                            write_frame(self.wfile,
                                        {"ok": False,
                                         "error": f"{type(e).__name__}: {e}"})
                        _m_srv_bytes_in.inc(_meter.read - r0)
                        _m_srv_bytes_out.inc(_meter.written - w0)
                except (ConnectionError, EOFError, IOError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return self._server.server_address

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def shutdown(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class RpcClient:
    """Blocking client. Reconnects a broken socket before the NEXT call,
    but never retransmits a frame that may already have been delivered —
    push_grad is not idempotent, and a retransmitted gradient would be
    applied twice. The timeout exceeds the server's 120s sync-barrier
    wait so a slow round can't masquerade as a dead connection."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 180.0):
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self._addr = tuple(addr)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()

    def call(self, method: str, *args):
        t0 = time.perf_counter()
        with self._mu, _tracing.span("rpc.client.call", method=method):
            if self._sock is None:
                # connecting is side-effect-free: retry once
                for attempt in (0, 1):
                    try:
                        self._sock = socket.create_connection(
                            self._addr, timeout=self._timeout)
                        break
                    except OSError:
                        if attempt:  # both attempts failed: a real error
                            _m_cli_errors.inc()
                            raise
                        _m_cli_retries.inc()
                self._rfile = self._sock.makefile("rb")
                self._wfile = self._sock.makefile("wb")
            r0, w0 = _meter.read, _meter.written
            try:
                write_msg(self._wfile, {"method": method, "args": list(args)})
                msg = read_msg(self._rfile)
            except (ConnectionError, OSError) as e:
                (_m_cli_timeouts if isinstance(e, socket.timeout)
                 else _m_cli_errors).inc()
                self.close_locked()
                raise
            finally:
                _m_cli_bytes_out.inc(_meter.written - w0)
                _m_cli_bytes_in.inc(_meter.read - r0)
            if msg is None:
                _m_cli_errors.inc()
                self.close_locked()
                raise ConnectionError("server closed mid-call")
            resp, segs = msg
        _metrics.histogram(f"rpc.client.{method}.ms").observe(
            (time.perf_counter() - t0) * 1e3)
        if not resp.get("ok"):
            _m_cli_errors.inc()
            raise RuntimeError(f"RPC {method} failed: {resp.get('error')}")
        return from_wire(resp.get("result"), segs)

    def close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._mu:
            self.close_locked()
