"""Length-prefixed JSON RPC framing — the transport under the parameter
server (and the same wire shape the master service uses,
distributed/master.py:serve). One message = 4-byte little-endian length +
UTF-8 JSON header, then zero or more RAW binary segments (lengths listed
in the header's "__segs__"). Tensors ride as raw segments — no base64
inflation, no JSON number lists — matching the reference transport's
zero-copy intent (operators/detail/sendrecvop_utils.cc serializes
VariableMessage as name + type + dims + chunked raw bytes; the proto is
send_recv.proto:17). Small/legacy frames may still inline tensors as
base64 blobs; both decode. Nothing needs pickle, so a hostile peer can at
worst force a parse error or a dropped connection.
"""
from __future__ import annotations

import base64
import json
import random
import socket
import socketserver
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from . import faults as _faults
from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger

_log = get_logger("rpc")

# the JSON header is small once tensors ride as segments: 16 MiB is roomy
MAX_FRAME = 16 << 20
# raw tensor segments per message: 1 GiB total
MAX_SEGMENT_BYTES = 1 << 30


class FrameTooLargeError(IOError):
    """A payload failed the SENDER-side size pre-flight (nothing hit the
    wire). Deterministic and actionable ("shard the tensor") — the retry
    loop must re-raise it untouched, never burn its budget re-sending
    the same oversized payload and bury the cause in a ConnectionError."""


class _ByteMeter(threading.local):
    """Per-thread wire-byte tally. read_frame/write_frame credit it as
    bytes cross the socket; RpcClient.call and the server handler
    snapshot it around each message to attribute deltas to their side's
    counters. Thread-local so concurrent client threads and server
    handler threads never share (or contend on) an accumulator."""

    def __init__(self):
        self.read = 0
        self.written = 0


_meter = _ByteMeter()

# client-side observability: per-method latency histograms are created on
# first use (method sets are small); byte/retry/timeout counters are flat
_m_cli_bytes_out = _metrics.counter("rpc.client.bytes_out")
_m_cli_bytes_in = _metrics.counter("rpc.client.bytes_in")
_m_cli_conn_retries = _metrics.counter("rpc.client.connect_retries")
# retransmissions: retry attempts after a prior attempt began writing the
# request frame (the server MAY have received it — the dedup cache is what
# makes resending correct). For plans that only drop RESPONSE frames this
# equals rpc.server.dedup_hits exactly: every such drop implies delivery,
# and every retransmit of a delivered frame is answered from the cache.
_m_cli_retries = _metrics.counter("rpc.client.retries")
_m_cli_timeouts = _metrics.counter("rpc.client.timeouts")
_m_cli_errors = _metrics.counter("rpc.client.errors")
_m_srv_bytes_out = _metrics.counter("rpc.server.bytes_out")
_m_srv_bytes_in = _metrics.counter("rpc.server.bytes_in")
_m_srv_errors = _metrics.counter("rpc.server.errors")
_m_srv_dedup = _metrics.counter("rpc.server.dedup_hits")


def to_wire(obj, segs: Optional[list] = None):
    """JSON-encode numpy arrays and SelectedRows. With `segs` (a list to
    append to), tensor bytes become out-of-band raw segments referenced by
    index; without it they inline as base64 (legacy/small-frame form)."""
    from ..fluid.selected_rows import SelectedRows, is_selected_rows

    if is_selected_rows(obj):
        return {"__sr__": {
            "rows": to_wire(np.asarray(obj.rows), segs),
            "value": to_wire(np.asarray(obj.value), segs),
            "height": int(obj.height),
        }}
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        spec = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
        if segs is not None:
            spec["seg"] = len(segs)
            segs.append(arr.tobytes())
        else:
            spec["b64"] = base64.b64encode(arr.tobytes()).decode("ascii")
        return {"__nd__": spec}
    if isinstance(obj, dict):
        return {k: to_wire(v, segs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v, segs) for v in obj]
    return obj


def from_wire(obj, segs: Optional[list] = None, copy: bool = True):
    """Decode a wire object. ``copy=True`` (default) materializes each
    tensor as a fresh writable array. ``copy=False`` returns NON-
    WRITEABLE views straight over the frame's segment bytes — zero
    receive-side copies, the right mode for read-path results
    (get_param pulls) that are immediately consumed by `jnp.asarray` /
    math; callers that need to mutate in place must copy themselves
    (numpy raises on write, so misuse is loud, never silent
    corruption). The view pins its frame's bytes alive exactly as long
    as the array — same peak memory as the copy, minus the copy."""
    from ..fluid.selected_rows import SelectedRows

    if isinstance(obj, dict):
        if "__nd__" in obj and len(obj) == 1:
            spec = obj["__nd__"]
            if "seg" in spec:
                if segs is None:
                    raise ValueError("segment-encoded tensor in a message "
                                     "read without segments")
                raw = segs[int(spec["seg"])]
            else:
                raw = base64.b64decode(spec["b64"])
            arr = np.frombuffer(
                raw, dtype=np.dtype(spec["dtype"])
            ).reshape(spec["shape"])
            # frombuffer over immutable bytes is already read-only; the
            # copy is what makes it writable (and owner of its memory)
            return arr.copy() if copy else arr
        if "__sr__" in obj and len(obj) == 1:
            spec = obj["__sr__"]
            return SelectedRows(
                from_wire(spec["rows"], segs, copy),
                from_wire(spec["value"], segs, copy),
                int(spec["height"]),
            )
        return {k: from_wire(v, segs, copy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [from_wire(v, segs, copy) for v in obj]
    return obj


def read_frame(rfile, max_frame: int = MAX_FRAME) -> Optional[dict]:
    head = rfile.read(4)
    if len(head) != 4:
        return None
    (n,) = struct.unpack("<I", head)
    if n > max_frame:
        raise IOError(f"frame of {n} bytes exceeds cap")
    body = rfile.read(n)
    if len(body) != n:
        return None
    _meter.read += 4 + n
    return json.loads(body.decode("utf-8"))


def write_frame(wfile, obj: dict, max_frame: int = MAX_FRAME):
    out = json.dumps(obj).encode("utf-8")
    if len(out) > max_frame:
        # fail HERE with the cause — the receiver would just drop the
        # connection, and the sender would retry the same oversized
        # payload forever behind an opaque ConnectionError
        raise FrameTooLargeError(
            f"frame of {len(out)} bytes exceeds the {max_frame}-byte cap "
            "(tensor too large for one RPC — shard it)"
        )
    wfile.write(struct.pack("<I", len(out)) + out)
    wfile.flush()
    _meter.written += 4 + len(out)


def write_msg(wfile, obj, max_frame: int = MAX_FRAME):
    """Encode `obj` (tensors as raw segments) and write header + segments.
    All size checks happen BEFORE the first byte hits the socket, so an
    oversized payload raises IOError with the stream still clean — the
    caller can still send a small error frame on the same connection."""
    segs: list = []
    wire = to_wire(obj, segs)
    total = sum(len(s) for s in segs)
    if total > MAX_SEGMENT_BYTES:
        raise FrameTooLargeError(
            f"message tensors total {total} bytes, exceeding the "
            f"{MAX_SEGMENT_BYTES}-byte cap (shard the tensor)"
        )
    if segs:
        wire = {"__segs__": [len(s) for s in segs], **wire} \
            if isinstance(wire, dict) else {"__segs__": [len(s) for s in segs],
                                            "__body__": wire}
    write_frame(wfile, wire, max_frame)
    for s in segs:
        wfile.write(s)
        _meter.written += len(s)
    if segs:
        wfile.flush()


def read_msg(rfile, max_frame: int = MAX_FRAME):
    """Read one header frame + its raw segments. Returns (obj, segs) with
    tensors NOT yet decoded — pass both to from_wire — or None on EOF."""
    obj = read_frame(rfile, max_frame)
    if obj is None:
        return None
    segs: list = []
    if isinstance(obj, dict) and "__segs__" in obj:
        lens = obj.pop("__segs__")
        # validate EVERY length individually: a negative entry would turn
        # rfile.read(-1) into a read-until-EOF hang, and mixed
        # negative/huge entries could cancel out in a sum-only check
        total = 0
        for n in lens:
            n = int(n)
            if n < 0 or n > MAX_SEGMENT_BYTES:
                raise IOError(f"bad segment length {n}")
            total += n
            if total > MAX_SEGMENT_BYTES:
                raise IOError("declared segments exceed the byte cap")
        for n in lens:
            b = rfile.read(int(n))
            if len(b) != int(n):
                return None
            _meter.read += len(b)
            segs.append(b)
        if "__body__" in obj and len(obj) == 1:
            obj = obj["__body__"]
    return obj, segs


class _DedupCache:
    """Bounded (client_id, seq) -> response cache — the server half of
    the idempotency-token protocol that makes client retransmits SAFE.

    `begin(rid)` either claims the id (first delivery: the caller must
    run the handler, then `finish` with the response) or returns the
    existing entry (retransmit: the caller waits for the original
    in-flight execution to finish and resends ITS response — the
    handler must not run twice, which for push_grad is the whole
    point). In-flight entries carry an Event so a retransmit that races
    the original's (slow) execution blocks instead of re-executing.

    Bounded: past `cap` entries, COMPLETED responses older than
    `min_age` seconds are dropped, oldest first. A retransmit arriving
    after eviction would re-execute, so eviction must never outrun the
    client's retry window — which is dominated by the PER-ATTEMPT
    socket timeout, not the backoff sleeps: a black-holed response
    means the client parks in read for its full timeout (180 s default)
    before retransmitting the same token. In-flight entries are never
    evicted (a racing retransmit must find the original, not re-run
    the handler), and completed ones are held for at least `min_age` —
    sized past 4 default-timeout attempts — even if that temporarily
    overshoots `cap` under a burst. A hard limit of 4x cap is the
    memory safety valve: past it the oldest completed entries go
    regardless of age (a retransmit landing after THAT is the
    documented residual risk; entries are small because large reads
    are declared idempotent and skip this cache entirely)."""

    def __init__(self, cap: int = 1024, min_age: float = 900.0):
        self._cap = cap
        self._min_age = float(min_age)
        self._mu = threading.Lock()
        self._entries: "OrderedDict[tuple, dict]" = \
            OrderedDict()  # guarded-by: _mu
        # lifetime retransmit answers, for stats()
        self._hits = 0  # guarded-by: _mu

    def stats(self) -> Dict[str, int]:
        """Introspection for /statusz: size, in-flight count, lifetime
        hits. O(n) over a bounded cache, only on debug-server scrapes."""
        with self._mu:
            in_flight = sum(1 for e in self._entries.values()
                            if not e["ev"].is_set())
            return {"entries": len(self._entries), "in_flight": in_flight,
                    "hits": self._hits, "cap": self._cap}

    def begin(self, rid):
        with self._mu:
            e = self._entries.get(rid)
            if e is not None:
                self._entries.move_to_end(rid)
                self._hits += 1
                return e, False
            e = {"ev": threading.Event(), "resp": None, "t": None}
            self._entries[rid] = e
            n = len(self._entries)
            if n > self._cap:
                now = time.monotonic()
                aged = [k for k, v in self._entries.items()
                        if v["ev"].is_set()
                        and now - v["t"] >= self._min_age]
                drop = aged[:n - self._cap]
                if n - len(drop) > 4 * self._cap:  # safety valve
                    done = [k for k, v in self._entries.items()
                            if v["ev"].is_set()]
                    drop = done[:n - self._cap]
                for k in drop:
                    del self._entries[k]
            return e, True

    @staticmethod
    def finish(entry, resp):
        entry["resp"] = resp
        entry["t"] = time.monotonic()
        entry["ev"].set()

    @staticmethod
    def wait(entry, timeout: float = 3600.0):
        # generous: the original may legitimately be a barrier parked for
        # a whole slow sync round — a waiter giving up earlier than the
        # barrier channel's client timeout would manufacture failures
        if entry["ev"].wait(timeout):
            return entry["resp"]
        return {"ok": False,
                "error": "duplicate call: original still executing"}


class RpcServer:
    """Threaded JSON-RPC server over a method dispatch table.

    `idempotent`: method names whose re-execution is harmless (reads
    like get_param). Their responses skip the dedup cache — retransmits
    just re-run them — so a server streaming large tensors never pins
    up to `dedup_cap` response arrays in the cache. Everything else
    (push_grad!) goes through the exactly-once dedup protocol."""

    def __init__(self, methods: Dict[str, Callable], dedup_cap: int = 1024,
                 idempotent: Optional[set] = None):
        self._methods = dict(methods)
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._dedup = _DedupCache(dedup_cap)
        self._idempotent = frozenset(idempotent or ())

    def stats(self) -> Dict[str, Any]:
        """Transport introspection for the debug server's /statusz:
        registered methods, idempotent set, and dedup-cache occupancy."""
        return {
            "methods": sorted(self._methods),
            "idempotent": sorted(self._idempotent),
            "dedup": self._dedup.stats(),
        }

    def serve(self, host: str = "127.0.0.1", port: int = 0
              ) -> Tuple[str, int]:
        methods = self._methods
        dedup = self._dedup
        idempotent = self._idempotent

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    while True:
                        r0, w0 = _meter.read, _meter.written
                        try:
                            msg = read_msg(self.rfile)
                        except (json.JSONDecodeError, UnicodeDecodeError) as e:
                            # malformed header: report, then CLOSE. If the
                            # unparseable header declared __segs__, their raw
                            # bytes are still on the wire and cannot be
                            # skipped — reading on would parse tensor bytes
                            # as the next length prefix and silently desync
                            _m_srv_errors.inc()
                            _log.error(
                                "bad frame from %s: %s",
                                self.client_address, e)
                            write_frame(self.wfile,
                                        {"ok": False,
                                         "error": f"bad frame: {e}"})
                            return
                        if msg is None:
                            return
                        req, segs = msg
                        method = req.get("method", "?")
                        # trace context stamped by a tracing-enabled
                        # client: adopt it so this handler's span (and
                        # everything under it) joins the client's trace,
                        # and answer the flow event so Perfetto draws the
                        # client→server arrow. Popped BEFORE dispatch so
                        # handlers never see the header as an argument.
                        wire_tr = req.pop("__trace__", None)
                        # idempotency token: [client_id, seq] stamped by
                        # RpcClient; frames without one (legacy/foreign
                        # peers) execute unconditionally as before
                        rid = req.get("id")
                        entry = first = None
                        if (isinstance(rid, list) and len(rid) == 2
                                and isinstance(rid[1], int)
                                and method not in idempotent):
                            entry, first = dedup.begin(
                                (str(rid[0]), rid[1]))
                            if not first:
                                # retransmit: ack from the cache — the
                                # handler already ran (or is running)
                                _m_srv_dedup.inc()
                                _log.info(
                                    "dedup hit for %r id=%s from %s",
                                    method, rid, self.client_address)
                                self._respond(method, dedup.wait(entry),
                                              traced=wire_tr is not None)
                                _m_srv_bytes_in.inc(_meter.read - r0)
                                _m_srv_bytes_out.inc(_meter.written - w0)
                                continue
                        t0 = time.perf_counter()
                        # span named per-method ("rpc.server.push_grad")
                        # so the merged timeline reads without arg
                        # inspection; the metric-name hostile-peer concern
                        # doesn't apply — spans land in a bounded ring,
                        # not the process-wide registry
                        with _tracing.adopt(wire_tr), \
                                _tracing.span(f"rpc.server.{method}",
                                              method=method):
                            if wire_tr:
                                _tracing.flow_end(wire_tr.get("f"))
                            try:
                                _faults.fire(f"handler.{method}")
                                fn = methods.get(method)
                                if fn is None:
                                    raise ValueError(
                                        f"unknown RPC method {method!r}")
                                result = fn(
                                    *from_wire(req.get("args", []), segs))
                                resp = {"ok": True, "result": result}
                            except Exception as e:  # report, keep serving
                                # handler failures used to surface only
                                # client-side; name the method and peer so
                                # the server's log carries the evidence
                                _m_srv_errors.inc()
                                _log.error(
                                    "handler %r failed for peer %s: "
                                    "%s: %s", method, self.client_address,
                                    type(e).__name__, e)
                                resp = {"ok": False,
                                        "error": f"{type(e).__name__}: {e}"}
                        if entry is not None:
                            # cache BEFORE responding: a response lost on
                            # the wire must find its answer here when the
                            # client retransmits
                            dedup.finish(entry, resp)
                        if method in methods:
                            # per-method only for REGISTERED methods — a
                            # hostile peer must not mint unbounded metric
                            # names into the process-wide registry
                            _metrics.histogram(
                                f"rpc.server.{method}.ms").observe(
                                    (time.perf_counter() - t0) * 1e3)
                        self._respond(method, resp,
                                      traced=wire_tr is not None)
                        _m_srv_bytes_in.inc(_meter.read - r0)
                        _m_srv_bytes_out.inc(_meter.written - w0)
                except (ConnectionError, EOFError, IOError):
                    return

            def _respond(self, method, resp, traced=False):
                if traced:
                    # clock handshake for `timeline merge`: the server's
                    # wall time rides the response; the client brackets
                    # it with its own send/recv times (NTP-style) and
                    # feeds tracing.note_clock_offset. A COPY — the
                    # dedup cache must keep the unstamped original
                    resp = {**resp, "__ts_srv__": time.time() * 1e6}
                self._respond_raw(method, resp)

            def _respond_raw(self, method, resp):
                try:
                    write_msg(self.wfile, resp)
                except IOError as e:
                    # oversized response (caught before any byte was
                    # written): tell the CLIENT why instead of
                    # dropping the connection into an opaque
                    # "server closed mid-call"
                    _m_srv_errors.inc()
                    _log.error(
                        "oversized response to %r for peer %s: %s",
                        method, self.client_address, e)
                    write_frame(self.wfile,
                                {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"})

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

            def __init__(self, addr, handler_cls):
                super().__init__(addr, handler_cls)
                # established connections, tracked so kill() can sever
                # them the way a SIGKILLed process's sockets die —
                # shutdown() alone only closes the LISTENER, and an
                # in-process chaos "kill" that leaves accepted
                # connections answering proves nothing about failover
                self._conns_mu = threading.Lock()
                self._conns: set = set()
                self._severed = False  # guarded-by: _conns_mu

            @staticmethod
            def _sever(conn):
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass

            def track(self, conn, on: bool):
                with self._conns_mu:
                    if on and self._severed:
                        # a handler thread whose accept raced kill():
                        # it reached setup() only after sever_all()
                        # snapshotted the set — without this late kill
                        # the connection would survive the "SIGKILL"
                        # and keep answering
                        late_kill = True
                    else:
                        late_kill = False
                        (self._conns.add if on
                         else self._conns.discard)(conn)
                if late_kill:
                    self._sever(conn)

            def sever_all(self):
                with self._conns_mu:
                    self._severed = True
                    conns = list(self._conns)
                    self._conns.clear()
                for c in conns:
                    self._sever(c)

        class TrackedHandler(Handler):
            def setup(self):
                super().setup()
                self.server.track(self.connection, True)

            def finish(self):
                self.server.track(self.connection, False)
                super().finish()

        self._server = Server((host, port), TrackedHandler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return self._server.server_address

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def shutdown(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def kill(self):
        """Abrupt transport death for chaos tests: stop accepting AND
        sever every ESTABLISHED connection, so peers mid-call see a
        connection reset — what a SIGKILLed process's sockets do.
        Nothing else is torn down: handlers that were executing keep
        running to completion (their replies go nowhere), exactly like
        work in flight when a real process dies mid-reply."""
        if self._server is not None:
            self._server.shutdown()
            self._server.sever_all()
            self._server.server_close()
            self._server = None


class RpcClient:
    """Blocking client with SAFE retries. Every request frame carries an
    idempotency token ``id = [client_id, seq]``; the server's dedup
    cache answers a retransmitted frame from the original response
    without re-running the handler, so resending a push_grad whose
    response was lost cannot apply the gradient twice — which is what
    makes retrying on a dropped connection correct at all (the old
    client reconnected but never retransmitted, so ONE lost frame
    failed the whole step). Connection failures retry with exponential
    backoff + jitter up to a bounded budget; application errors
    (``ok: false`` responses) are delivered results and never retried.
    The default timeout exceeds the server's default 120s sync-barrier
    wait so a slow round can't masquerade as a dead connection; barrier
    channels use param_server.BARRIER_CLIENT_TIMEOUT, which outlasts
    any configurable barrier_timeout."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 180.0,
                 retries: int = 3, backoff: float = 0.05,
                 connect_timeout: Optional[float] = None):
        """`timeout` bounds each read/write; `connect_timeout` bounds the
        DIAL only (default: min(timeout, 30s)) — a channel that
        legitimately waits hours for a response (barrier) must still
        discover a black-holed host in seconds, not inherit the long
        read timeout into every SYN."""
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self._addr = tuple(addr)
        self._timeout = timeout
        self._connect_timeout = (min(timeout, 30.0)
                                 if connect_timeout is None
                                 else float(connect_timeout))
        self._retries = max(0, int(retries))
        self._backoff = float(backoff)
        # connection state + token sequence all ride _mu — the same lock
        # that serializes call() on this client's single connection
        self._sock: Optional[socket.socket] = None  # guarded-by: _mu
        self._rfile = None  # guarded-by: _mu
        self._wfile = None  # guarded-by: _mu
        self._mu = threading.Lock()
        # token namespace: unique per client INSTANCE (uuid, not addr) —
        # two clients to one server must never collide in its dedup cache
        self._client_id = uuid.uuid4().hex[:16]
        self._seq = 0  # guarded-by: _mu

    def call(self, method: str, *args, copy_result: bool = True):
        """``copy_result=False``: tensors in the response come back as
        read-only views over the received frame bytes — zero receive-
        side copies, for read-path results (get_param/get_rows pulls)
        that feed straight into `jnp.asarray`/math. The default stays a
        writable copy so callers that mutate results in place keep
        working."""
        t0 = time.perf_counter()
        # lint: allow-blocking — _mu deliberately serializes calls (and
        # their retry sleeps) on this client's single connection: two
        # threads interleaving frames on one socket would corrupt the
        # stream. Blocking callers park here by design; use a separate
        # channel (get_client(ep, channel=...)) for isolation.
        with self._mu, _tracing.span(f"rpc.client.{method}",
                                     method=method):
            self._seq += 1
            req = {"method": method, "args": list(args),
                   "id": [self._client_id, self._seq]}
            if _tracing.trace_enabled():
                # one flow id per LOGICAL call (retransmits share it —
                # the server answers whichever delivery executed): the
                # idempotency token already names the call uniquely
                fid = f"{self._client_id}:{self._seq}"
                wire_tr = _tracing.wire_context(fid)
                if wire_tr is not None:
                    req["__trace__"] = wire_tr
                    _tracing.flow_start(fid)
            sent_any = False
            last_err: Optional[Exception] = None
            for attempt in range(self._retries + 1):
                if attempt:
                    if sent_any:
                        _m_cli_retries.inc()  # a true retransmission
                    else:
                        _m_cli_conn_retries.inc()
                    # exponential backoff with jitter, capped: spreads a
                    # thundering herd of trainers re-dialing a restarted
                    # pserver without stretching recovery into minutes
                    delay = min(self._backoff * (2 ** (attempt - 1)), 2.0)
                    time.sleep(delay * (0.5 + random.random() * 0.5))
                try:
                    resp, segs = self._attempt(method, req)
                    break
                except FrameTooLargeError:
                    # deterministic sender-side pre-flight failure:
                    # resending the same payload can never succeed —
                    # surface the "shard it" diagnosis directly
                    _m_cli_errors.inc()
                    raise
                except (ConnectionError, OSError) as e:
                    (_m_cli_timeouts if isinstance(e, socket.timeout)
                     else _m_cli_errors).inc()
                    sent_any = sent_any or getattr(e, "_after_send", False)
                    self.close_locked()
                    last_err = e
            else:
                raise ConnectionError(
                    f"RPC {method} to {self._addr} failed after "
                    f"{self._retries + 1} attempts: {last_err}"
                ) from last_err
        _metrics.histogram(f"rpc.client.{method}.ms").observe(
            (time.perf_counter() - t0) * 1e3)
        if not resp.get("ok"):
            _m_cli_errors.inc()
            raise RuntimeError(f"RPC {method} failed: {resp.get('error')}")
        return from_wire(resp.get("result"), segs, copy=copy_result)

    def _attempt(self, method: str, req: dict):
        """One connect+send+recv try. Exceptions are tagged with
        `_after_send` once the request frame started down the wire, so
        the retry loop can tell a retransmission (counts toward
        rpc.client.retries, may hit the server's dedup cache) from a
        never-sent re-dial."""
        if self._sock is None:
            _faults.fire("connect")
            self._sock = socket.create_connection(
                self._addr, timeout=self._connect_timeout)
            self._sock.settimeout(self._timeout)
            self._rfile = self._sock.makefile("rb")
            self._wfile = self._sock.makefile("wb")
        r0, w0 = _meter.read, _meter.written
        sent = False
        try:
            _faults.fire(f"call.{method}")  # delay rules sleep here
            try:
                _faults.fire(f"send.{method}")
            except _faults.InjectedFault:
                # simulate a MID-FRAME disconnect: a dangling length
                # prefix with a truncated body, then the connection dies
                # — the server must discard it without desyncing
                try:
                    self._wfile.write(struct.pack("<I", 64) + b"\x7f")
                    self._wfile.flush()
                except OSError:
                    pass
                raise
            t_send = time.time()
            write_msg(self._wfile, req)
            sent = True
            _faults.fire(f"recv.{method}")  # response lost after delivery
            msg = read_msg(self._rfile)
        except (ConnectionError, OSError) as e:
            e._after_send = sent
            raise
        finally:
            _m_cli_bytes_out.inc(_meter.written - w0)
            _m_cli_bytes_in.inc(_meter.read - r0)
        if msg is None:
            err = ConnectionError("server closed mid-call")
            err._after_send = True
            raise err
        obj, segs = msg
        if isinstance(obj, dict) and "__ts_srv__" in obj:
            # NTP-style offset sample: the server stamped its wall time
            # mid-round-trip; the midpoint of our send/recv brackets it,
            # so (server - midpoint) estimates the clock skew `timeline
            # merge` corrects for. Popped so callers never see it.
            srv_us = obj.pop("__ts_srv__")
            _tracing.note_clock_offset(
                float(srv_us) - (t_send + time.time()) / 2.0 * 1e6)
        return obj, segs

    def close_locked(self):
        # close the makefile objects too: they hold their own references
        # to the socket's fd, and a client that cycles through many
        # broken connections would otherwise leak both wrappers per cycle
        for f in (self._rfile, self._wfile):
            if f is not None:
                try:
                    f.close()
                except (OSError, ValueError):
                    pass
        self._rfile = self._wfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._mu:
            self.close_locked()
