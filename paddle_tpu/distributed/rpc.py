"""Length-prefixed JSON RPC framing — the transport under the parameter
server (and the same wire shape the master service uses,
distributed/master.py:serve). One frame = 4-byte little-endian length +
UTF-8 JSON. Tensors ride as tagged base64 blobs; nothing needs pickle, so
a hostile peer can at worst force a parse error or a dropped connection
(the reference's in-cluster transport is protobuf for the same reason —
operators/detail/send_recv.proto:17 VariableMessage = name + type + dims +
chunked raw bytes).
"""
from __future__ import annotations

import base64
import json
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

# tensors are bigger than master-service task lists: cap frames at 256 MiB
# (a bs=8192 f32 [8192, 4096] embedding push is ~128 MiB)
MAX_FRAME = 256 << 20


def to_wire(obj):
    """JSON-encode numpy arrays and SelectedRows as tagged blobs."""
    from ..fluid.selected_rows import SelectedRows, is_selected_rows

    if is_selected_rows(obj):
        return {"__sr__": {
            "rows": to_wire(np.asarray(obj.rows)),
            "value": to_wire(np.asarray(obj.value)),
            "height": int(obj.height),
        }}
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        return {"__nd__": {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
        }}
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def from_wire(obj):
    from ..fluid.selected_rows import SelectedRows

    if isinstance(obj, dict):
        if "__nd__" in obj and len(obj) == 1:
            spec = obj["__nd__"]
            arr = np.frombuffer(
                base64.b64decode(spec["b64"]), dtype=np.dtype(spec["dtype"])
            ).reshape(spec["shape"])
            return arr.copy()  # writable, owns its memory
        if "__sr__" in obj and len(obj) == 1:
            spec = obj["__sr__"]
            return SelectedRows(
                from_wire(spec["rows"]), from_wire(spec["value"]),
                int(spec["height"]),
            )
        return {k: from_wire(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [from_wire(v) for v in obj]
    return obj


def read_frame(rfile, max_frame: int = MAX_FRAME) -> Optional[dict]:
    head = rfile.read(4)
    if len(head) != 4:
        return None
    (n,) = struct.unpack("<I", head)
    if n > max_frame:
        raise IOError(f"frame of {n} bytes exceeds cap")
    body = rfile.read(n)
    if len(body) != n:
        return None
    return json.loads(body.decode("utf-8"))


def write_frame(wfile, obj: dict, max_frame: int = MAX_FRAME):
    out = json.dumps(obj).encode("utf-8")
    if len(out) > max_frame:
        # fail HERE with the cause — the receiver would just drop the
        # connection, and the sender would retry the same oversized
        # payload forever behind an opaque ConnectionError
        raise IOError(
            f"frame of {len(out)} bytes exceeds the {max_frame}-byte cap "
            "(tensor too large for one RPC — shard it)"
        )
    wfile.write(struct.pack("<I", len(out)) + out)
    wfile.flush()


class RpcServer:
    """Threaded JSON-RPC server over a method dispatch table."""

    def __init__(self, methods: Dict[str, Callable]):
        self._methods = dict(methods)
        self._server: Optional[socketserver.ThreadingTCPServer] = None

    def serve(self, host: str = "127.0.0.1", port: int = 0
              ) -> Tuple[str, int]:
        methods = self._methods

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    while True:
                        try:
                            req = read_frame(self.rfile)
                        except json.JSONDecodeError as e:
                            # malformed but well-framed: report, keep serving
                            write_frame(self.wfile,
                                        {"ok": False,
                                         "error": f"bad frame: {e}"})
                            continue
                        if req is None:
                            return
                        try:
                            fn = methods.get(req["method"])
                            if fn is None:
                                raise ValueError(
                                    f"unknown RPC method {req['method']!r}")
                            result = fn(*from_wire(req.get("args", [])))
                            resp = {"ok": True, "result": to_wire(result)}
                        except Exception as e:  # report, keep serving
                            resp = {"ok": False,
                                    "error": f"{type(e).__name__}: {e}"}
                        write_frame(self.wfile, resp)
                except (ConnectionError, EOFError, IOError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return self._server.server_address

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def shutdown(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class RpcClient:
    """Blocking client. Reconnects a broken socket before the NEXT call,
    but never retransmits a frame that may already have been delivered —
    push_grad is not idempotent, and a retransmitted gradient would be
    applied twice. The timeout exceeds the server's 120s sync-barrier
    wait so a slow round can't masquerade as a dead connection."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 180.0):
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self._addr = tuple(addr)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()

    def call(self, method: str, *args):
        with self._mu:
            if self._sock is None:
                # connecting is side-effect-free: retry once
                for attempt in (0, 1):
                    try:
                        self._sock = socket.create_connection(
                            self._addr, timeout=self._timeout)
                        break
                    except OSError:
                        if attempt:
                            raise
                self._rfile = self._sock.makefile("rb")
                self._wfile = self._sock.makefile("wb")
            try:
                write_frame(self._wfile,
                            {"method": method, "args": to_wire(args)})
                resp = read_frame(self._rfile)
            except (ConnectionError, OSError):
                self.close_locked()
                raise
            if resp is None:
                self.close_locked()
                raise ConnectionError("server closed mid-call")
        if not resp.get("ok"):
            raise RuntimeError(f"RPC {method} failed: {resp.get('error')}")
        return from_wire(resp.get("result"))

    def close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._mu:
            self.close_locked()
