"""Leader election + standby takeover for the master data service.

Capability parity with the reference's etcd-backed HA (go/master/
etcd_client.go: campaign on a lease key, lose-lease -> step down;
go/pserver/etcd_client.go:70-204: TTL-lease registration that clients
re-resolve). TPU-era redesign without an etcd dependency: the lease lives
in a file on shared storage, mutual exclusion via flock, and the elected
master publishes its TCP endpoint next to the lease for clients to
re-resolve.

Filesystem requirement: the lease path must live on a filesystem with
WORKING POSIX advisory locks — local disk (multi-process single host) or
NFSv4 with its lock manager. Object-store FUSE mounts (gcsfuse, s3fs) do
NOT implement flock; on those, two candidates could both win. For
cross-host deployments without lock-capable shared storage use
tcp_lease.LeaseServer/TcpLease (same lease surface over the master RPC
framing — pass `lease=TcpLease(...)` to ElectedMaster); the campaign/TTL
semantics are identical, so the swap is one constructor argument.
Defense-in-depth either way: a holder whose lease state is corrupted or
stolen under it steps down on the next renew() and its fenced() commits
raise MasterDeposed (tests/test_distributed.py adversarial-swap test).
On takeover the new leader recovers the queue from the shared snapshot
(master.py snapshot/recover), so leased work survives a master crash: the
pending leases it cannot see simply time out and re-queue.

    # on every master candidate (any number of processes):
    em = ElectedMaster(lease_path, snapshot_path, chunks_per_task=1)
    em.start()            # campaigns; serves while leader
    ...
    em.stop()

    # trainers:
    client = MasterClient(addr_resolver=endpoint_resolver(lease_path))
"""
from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from typing import Callable, Optional, Tuple

from .master import MasterService


class FileLease:
    """A TTL lease in a file, flock-serialized (role of an etcd lease).

    Layout: `<path>` holds JSON {"holder", "deadline", "endpoint"};
    `<path>.lock` is the flock target (kept separate so replacing the
    lease content never races the lock itself)."""

    def __init__(self, path: str, holder_id: str, ttl: float = 5.0):
        self.path = path
        self.holder = holder_id
        self.ttl = float(ttl)
        # monotonic fencing term: bumped each time the lease changes
        # hands (tcp_lease.LeaseServer semantics). Stamped into master
        # snapshots so stale-leader writes lose by term comparison, not
        # by timing.
        self.term = 0

    def _locked(self):
        lock = open(self.path + ".lock", "a+")
        fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
        return lock

    def _read(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _write(self, state: dict):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.path)

    def try_acquire(self, endpoint: Optional[Tuple[str, int]] = None) -> bool:
        """Become (or stay) holder if the lease is free, expired, or ours."""
        lock = self._locked()
        try:
            st = self._read()
            now = time.time()
            if (st.get("holder") not in (None, self.holder)
                    and st.get("deadline", 0) > now):
                return False
            term = (st.get("term", 0)
                    if st.get("holder") == self.holder
                    else st.get("term", 0) + 1)
            self._write({"holder": self.holder, "deadline": now + self.ttl,
                         "term": term,
                         "endpoint": list(endpoint) if endpoint else None})
            self.term = term
            return True
        finally:
            lock.close()

    def renew(self, endpoint: Optional[Tuple[str, int]] = None) -> bool:
        """Extend our lease; False (lost) if someone else holds it now."""
        lock = self._locked()
        try:
            st = self._read()
            if st.get("holder") != self.holder:
                return False
            self._write({"holder": self.holder,
                         "deadline": time.time() + self.ttl,
                         "term": st.get("term", self.term),
                         "endpoint": list(endpoint) if endpoint else None})
            return True
        finally:
            lock.close()

    def release(self):
        lock = self._locked()
        try:
            st = self._read()
            if st.get("holder") == self.holder:
                # keep the term: the next holder must get a HIGHER one
                self._write({"term": st.get("term", self.term)})
        finally:
            lock.close()

    def fenced(self, commit: Callable[[], None]):
        """Run `commit` atomically-with-respect-to-lease-transfer: under the
        lease lock, verify we still hold an unexpired lease, then commit.
        Raises MasterDeposed otherwise — the fencing that stops a stale
        leader from overwriting the new leader's state (the role of etcd
        transactions guarded on the lease key)."""
        from .master import MasterDeposed

        lock = self._locked()
        try:
            st = self._read()
            if (st.get("holder") != self.holder
                    or st.get("deadline", 0) <= time.time()):
                raise MasterDeposed(
                    f"{self.holder} no longer holds the lease "
                    f"(holder={st.get('holder')!r})")
            commit()
        finally:
            lock.close()

    def current(self) -> dict:
        lock = self._locked()
        try:
            return self._read()
        finally:
            lock.close()


def endpoint_resolver(lease_path: str) -> Callable[[], Tuple[str, int]]:
    """Resolver for MasterClient: returns the CURRENT leader's endpoint
    (reference: pserver clients re-list etcd keys on reconnect)."""

    def resolve() -> Tuple[str, int]:
        try:
            with open(lease_path) as f:
                st = json.load(f)
        except (OSError, ValueError):
            raise ConnectionError(f"no master lease at {lease_path}")
        ep = st.get("endpoint")
        if not ep or st.get("deadline", 0) <= time.time():
            raise ConnectionError("no live master holds the lease")
        return ep[0], int(ep[1])

    return resolve


class ElectedMaster:
    """A master candidate: campaigns for the lease; while leader, serves a
    MasterService recovered from the shared snapshot; steps down (stops
    serving) if the lease is lost."""

    def __init__(self, lease_path: Optional[str], snapshot_path: str,
                 holder_id: Optional[str] = None, ttl: float = 5.0,
                 host: str = "127.0.0.1", renew_interval: Optional[float] = None,
                 lease=None, **service_kwargs):
        # lease= swaps the coordination backend: any object with the
        # FileLease surface works (tcp_lease.TcpLease for storage without
        # trustworthy POSIX locks)
        self.lease = lease if lease is not None else FileLease(
            lease_path, holder_id or f"master-{os.getpid()}-{id(self):x}",
            ttl)
        self._snapshot_path = snapshot_path
        self._service_kwargs = service_kwargs
        self._host = host
        self._renew_every = renew_interval or ttl / 3.0
        self.service: Optional[MasterService] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.is_leader = threading.Event()
        # last failure from a leadership attempt (corrupt snapshot, bind
        # error, ...): surfaced so wait_leader() timeouts are diagnosable
        self.last_error: Optional[BaseException] = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def wait_leader(self, timeout: Optional[float] = None) -> bool:
        return self.is_leader.wait(timeout)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._step_down(release=True)

    def crash(self):
        """Test hook: die without releasing the lease (the takeover path —
        a standby must wait out the TTL, like a real master crash)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._step_down(release=False)

    # -- internals --------------------------------------------------------
    def _become_leader(self):
        self.service = MasterService(
            snapshot_path=self._snapshot_path,
            snapshot_fence=self.lease.fenced,
            # stamp snapshots with OUR leadership term: a deposed leader's
            # late commit loses by term comparison even if it slips past a
            # check-then-commit fence (see MasterService._snapshot_locked)
            snapshot_term=getattr(self.lease, "term", 0) or 0,
            **self._service_kwargs)
        self.addr = self.service.serve(host=self._host, port=0)
        if not self.lease.renew(self.addr):
            # startup (snapshot recovery / bind) outlasted the TTL and a
            # standby took the lease — we are NOT the leader; raising here
            # routes through _run's failure path (shutdown + retry)
            raise RuntimeError("lease lost during leader startup")
        self.is_leader.set()

    def _step_down(self, release: bool):
        self.is_leader.clear()
        if self.service is not None:
            self.service.shutdown()
            self.service = None
            self.addr = None
        if release:
            self.lease.release()

    def _run(self):
        while not self._stop.is_set():
            if self.service is None:
                if self.lease.try_acquire():
                    try:
                        self._become_leader()
                    except Exception as e:
                        # corrupt snapshot, bind failure, ...: don't die
                        # silently holding the lease — release it, record
                        # the failure, and keep campaigning (another
                        # candidate may have a healthier environment)
                        self.last_error = e
                        import sys as _sys

                        print(f"[election] {self.lease.holder} failed to "
                              f"become leader: {type(e).__name__}: {e}",
                              file=_sys.stderr)
                        self._step_down(release=True)
                        self._stop.wait(self._renew_every)
                else:
                    self._stop.wait(self._renew_every)
                    continue
            else:
                if not self.lease.renew(self.addr):
                    # split-brain guard: someone else won the lease — stop
                    # serving immediately (reference: lose etcd lease ->
                    # process exits)
                    self._step_down(release=False)
                    continue
                self._stop.wait(self._renew_every)
