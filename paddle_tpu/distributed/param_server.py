"""Executable parameter server — cross-process async/sync SGD.

The reference's pserver is `listen_and_serv_op.cc:78-192`: block on N
gradient sends, run per-param optimize sub-blocks via an Executor, handle
sparse SelectedRows grads, answer parameter gets. This is that capability
around OUR stack: an RPC service (distributed/rpc.py framing) wrapping an
Executor that runs the per-param slices of
`DistributeTranspiler.get_pserver_program(ep)`.

  - push_grad(name, grad[, trainer_id]) — grad is dense ndarray OR
    SelectedRows (rows/value/height ride the wire, the row-wise lazy
    optimizer ops apply them without densifying). async mode applies
    immediately (reference sync_mode=False); sync mode accumulates until
    all `trainers` have pushed, sums (dense add / SelectedRows concat —
    reference listen_and_serv_op.cc:181-192), applies once, and releases
    the barrier.
  - get_param(name) — current value from the pserver scope.
  - barrier() — sync mode: wait until the current round's updates applied
    (the reference's send_barrier_op).

Trainer side: `ParameterClient` (send/recv), or in-graph `send`/`recv`
ops the Executor runs as host ops (reference send_op.cc/recv_op.cc) —
see DistributeTranspiler.get_trainer_program(send_recv=True).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import metrics as _metrics, tracing as _tracing
from .rpc import RpcClient, RpcServer

__all__ = ["ParameterServer", "ParameterClient", "get_client"]

# ISSUE 1 instrumentation: push/pull volume counters plus the sync-mode
# barrier wait-time histogram — the number that shows straggler trainers
# (a fat p99 here IS the straggler, before anyone reads a timeline)
_m_push = _metrics.counter("pserver.push_grad")
_m_get = _metrics.counter("pserver.get_param")
_m_get_rows = _metrics.counter("pserver.get_rows")
_m_barrier_ms = _metrics.histogram("pserver.barrier_wait_ms")


class ParameterServer:
    """Runs the optimize slice of a pserver program behind RPC."""

    def __init__(self, pserver_program, startup_program=None,
                 trainers: int = 1, sync_mode: bool = False, scope=None):
        """startup_program initializes a fresh scope; alternatively pass an
        already-populated `scope` (the ListenAndServ in-process form, where
        the server shares the builder's state)."""
        import paddle_tpu.fluid as fluid

        if startup_program is None and scope is None:
            raise ValueError("need startup_program or a populated scope")
        self._trainers = max(1, int(trainers))
        self._sync = bool(sync_mode)
        self._scope = scope if scope is not None else fluid.Scope()
        self._exe = fluid.Executor()
        self._program = pserver_program
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._round = 0
        # sync: param -> {trainer_id: grad} — DISTINCT trainers complete a
        # round (a retransmitted push overwrites, it can't phantom-complete)
        self._pending: Dict[str, Dict[int, Any]] = {}
        self._applied_round: set = set()
        self._steps = 0
        # async: one lock per param (concurrent cross-param applies);
        # _shared_mu guards the cycle bookkeeping + counters, and
        # _shared_run_mu serializes the stateful LR-chain executions
        self._param_locks: Dict[str, threading.Lock] = {}
        self._shared_mu = threading.Lock()
        self._shared_run_mu = threading.Lock()
        # params applied since the shared (LR-decay) program last ran: the
        # shared chain advances once per DISTINCT-PARAM CYCLE — a repeat
        # push means a new optimization step started — not once per
        # len(owned) raw pushes, which drifts when a sparse workload skips
        # params in a step (ADVICE r3)
        self._applied_since_shared: set = set()

        block = pserver_program.global_block()
        self._owned = sorted(
            n for n, v in block.vars.items()
            if getattr(v.desc, "is_parameter", False)
        )
        self._param_locks = {p: threading.Lock() for p in self._owned}
        owned = set(self._owned)
        # Split the pserver program (reference listen_and_serv: per-param
        # optimize sub-blocks + ONE lr-decay sub-block run once per round):
        #  - shared STATEFUL ops (advance persistable non-param state, e.g.
        #    the LR-decay step counter) run once per round, not once per
        #    param-push — otherwise a 2-param pserver would decay the LR
        #    twice per step;
        #  - everything else shared (stateless arithmetic) stays in each
        #    per-param slice, where recomputing it is free.
        persistable = {n for n, v in block.vars.items() if v.persistable}
        shared_stateful = []
        for op in block.ops:
            outs = set(op.desc.output_names())
            if not (outs & owned) and (outs & (persistable - owned)):
                shared_stateful.append(op)
        shared_idx = {id(op) for op in shared_stateful}

        def _slice(keep_pred):
            prog = pserver_program.clone()
            b = prog.global_block()
            keep = [op for orig, op in zip(block.ops, b.ops)
                    if keep_pred(orig)]
            b.ops = keep
            used = set(owned)
            for op in keep:
                used.update(n for n in op.desc.input_names() if n)
                used.update(n for n in op.desc.output_names() if n)
            b.vars = {n: v for n, v in b.vars.items() if n in used}
            prog._bump_version()
            return prog

        self._shared_prog = None
        if shared_stateful:
            self._shared_prog = _slice(lambda op: id(op) in shared_idx)
        self._per_param: Dict[str, Any] = {}
        self._grad_name: Dict[str, str] = {}
        for p in self._owned:
            def keep(op, p=p):
                outs = set(op.desc.output_names())
                if id(op) in shared_idx:
                    return False
                return p in outs or not (outs & owned)

            self._per_param[p] = _slice(keep)
            # the grad feed name is whatever the optimize op's Grad input
            # actually is (clipping/regularization can rename it)
            gname = p + "@GRAD"
            for op in block.ops:
                if p in set(op.desc.output_names()):
                    g = (op.desc.inputs.get("Grad") or [gname])[0]
                    gname = g or gname
                    break
            self._grad_name[p] = gname

        if startup_program is not None:
            with fluid.scope_guard(self._scope):
                self._exe.run(startup_program)

        # traffic evidence for the sparse-prefetch contract (the round-3
        # verdict's acceptance test asserts trainer traffic is proportional
        # to batch ids, not table size); incremented from concurrent
        # handler threads, so guarded by their own lock
        self._stats_mu = threading.Lock()
        self._full_pull_rows = 0
        self._prefetch_rows = 0

        self._server = RpcServer({
            "get_param": self.get_param,
            "get_rows": self.get_rows,
            "push_grad": self.push_grad,
            "barrier": self.barrier,
            "owned_params": self.owned_params,
            "stats": self.stats,
        })

    # --- RPC methods ---------------------------------------------------
    def owned_params(self) -> List[str]:
        return list(self._owned)

    def stats(self) -> Dict[str, int]:
        """Evidence of server-side work: optimize steps applied + round +
        rows served via full pulls vs row-granular prefetches."""
        return {"steps": self._steps, "round": self._round,
                "sync": self._sync, "trainers": self._trainers,
                "full_pull_rows": self._full_pull_rows,
                "prefetch_rows": self._prefetch_rows}

    def get_param(self, name: str):
        if name not in self._owned:
            raise KeyError(f"param '{name}' is not owned by this pserver")
        _m_get.inc()
        v = self._scope.find_var(name)
        arr = np.asarray(v)
        with self._stats_mu:
            self._full_pull_rows += int(arr.shape[0]) if arr.ndim else 1
        return arr

    def get_rows(self, name: str, rows):
        """Row-granular pull: only the requested embedding rows ride the
        wire (reference prefetch_op.cc + the distributed-lookup-table
        design doc — the capability that lets a vocab far larger than one
        trainer's memory train efficiently)."""
        if name not in self._owned:
            raise KeyError(f"param '{name}' is not owned by this pserver")
        _m_get_rows.inc()
        rows = np.asarray(rows, dtype=np.int64).reshape(-1)
        table = np.asarray(self._scope.find_var(name))
        if rows.size and (rows.min() < 0 or rows.max() >= table.shape[0]):
            raise IndexError(
                f"prefetch rows out of range for '{name}' "
                f"[0, {table.shape[0]})"
            )
        with self._stats_mu:
            self._prefetch_rows += int(rows.size)
        return table[rows]

    def push_grad(self, name: str, grad, trainer_id: int = 0):
        if name not in self._owned:
            raise KeyError(f"param '{name}' is not owned by this pserver")
        _m_push.inc()
        if not self._sync:
            # hogwild-style async with PER-PARAM atomicity: updates to one
            # param serialize (an unserialized read-modify-write would drop
            # whole gradients), while different params apply CONCURRENTLY
            # from different handler threads — the reference pserver's
            # per-block locking (parameter_server2's block-sharded applies)
            with self._param_locks[name]:
                self._apply(name, grad)
            return {"step": self._steps, "round": self._round}
        with self._cv:
            # the round this grad belongs to, BEFORE any completion this
            # push might trigger — the trainer barriers on it (its whole
            # step's pushes share it: a round cannot complete without this
            # trainer's last push, so it can't advance mid-step)
            round_of_push = self._round
            self._pending.setdefault(name, {})[int(trainer_id)] = grad
            if len(self._pending[name]) >= self._trainers:
                merged = _merge_grads(list(self._pending.pop(name).values()))
                self._apply(name, merged)
                self._applied_round.add(name)
            # a round completes when EVERY owned param applied its merge
            # (an empty pending map alone is not enough — params not yet
            # pushed this round leave it empty too)
            if self._applied_round >= set(self._owned):
                self._applied_round.clear()
                self._round += 1
                self._cv.notify_all()
            return {"step": self._steps, "round": round_of_push}

    def barrier(self, known_round: Optional[int] = None):
        """Sync mode: block until round `known_round` (the value push_grad
        returned for this trainer's sends) has completed (reference
        send_barrier_op: send, barrier, recv). Waiting on a round NUMBER —
        not on queue emptiness — keeps a fast trainer's next-round pushes
        from wedging a slow trainer's barrier. known_round=None just
        reports the current round."""
        if not self._sync or known_round is None:
            return {"round": self._round}
        target = int(known_round) + 1
        t0 = time.perf_counter()
        with self._cv, _tracing.span("pserver.barrier", round=target):
            done = self._cv.wait_for(
                lambda: self._round >= target, timeout=120)
            _m_barrier_ms.observe((time.perf_counter() - t0) * 1e3)
            if not done:
                raise TimeoutError(
                    f"sync round {known_round} incomplete after 120s — a "
                    f"trainer died mid-round (pending: {list(self._pending)})"
                )
            return {"round": self._round}

    # --- internals -----------------------------------------------------
    def _apply(self, name: str, grad):
        """Caller holds this param's lock (async) or the big cv lock
        (sync). Cross-param concurrency is safe: per-param programs write
        disjoint scope names; the shared LR chain's cycle bookkeeping and
        its stateful execution take their own locks (an apply may read an
        LR mid-decay of a concurrent cycle boundary — the documented
        hogwild staleness, not a lost update)."""
        import paddle_tpu.fluid as fluid

        with fluid.scope_guard(self._scope):
            # shared stateful chain (LR-decay counters) advances once per
            # distinct-param cycle: at the first push ever, and whenever a
            # param REPEATS (its second push means a new step began)
            run_shared = False
            with self._shared_mu:
                if name in self._applied_since_shared or \
                        not self._applied_since_shared:
                    run_shared = self._shared_prog is not None
                    self._applied_since_shared = set()
                self._applied_since_shared.add(name)
            if run_shared:
                with self._shared_run_mu:
                    self._exe.run(self._shared_prog)
            with _tracing.span("pserver.apply", param=name):
                self._exe.run(self._per_param[name],
                              feed={self._grad_name[name]: grad})
        with self._shared_mu:
            self._steps += 1

    # --- lifecycle -----------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0
              ) -> Tuple[str, int]:
        return self._server.serve(host, port)

    @property
    def address(self):
        return self._server.address

    def shutdown(self):
        self._server.shutdown()


def _merge_grads(grads: List[Any]):
    """Sum a sync round's gradients (reference listen_and_serv_op.cc
    :181-192: dense sum / SelectedRows concat-then-merge)."""
    from ..fluid.selected_rows import SelectedRows, is_selected_rows

    if any(is_selected_rows(g) for g in grads):
        rows = np.concatenate([np.asarray(g.rows) for g in grads])
        value = np.concatenate([np.asarray(g.value) for g in grads])
        return SelectedRows(rows, value, grads[0].height)
    out = np.asarray(grads[0])
    for g in grads[1:]:
        out = out + np.asarray(g)
    return out


class ParameterClient:
    """Trainer-side client (reference operators/detail/grpc_client.cc +
    send_op/recv_op): push grads to / pull params from the pserver that
    owns each variable."""

    def __init__(self, assignment: Dict[str, str], trainer_id: int = 0):
        """assignment: param name -> "host:port" endpoint
        (DistributeTranspiler.param_assignment)."""
        self._assignment = dict(assignment)
        self._trainer_id = int(trainer_id)
        # endpoint -> round of this step's first send, consumed by barrier()
        self._send_round: Dict[str, int] = {}

    def _client(self, name: str) -> RpcClient:
        ep = self._assignment.get(name)
        if ep is None:
            raise KeyError(f"no pserver assignment for '{name}'")
        return get_client(ep)

    def send_grad(self, name: str, grad):
        resp = self._client(name).call("push_grad", name, grad,
                                       self._trainer_id)
        ep = self._assignment[name]
        if isinstance(resp, dict) and ep not in self._send_round:
            # remember which round this step's pushes joined, so a bare
            # barrier() can wait on the right round number
            self._send_round[ep] = resp.get("round")
        return resp

    def get_param(self, name: str) -> np.ndarray:
        return self._client(name).call("get_param", name)

    def get_rows(self, name: str, rows) -> np.ndarray:
        """Pull only the given rows of a (large) table — the trainer-side
        half of the reference's prefetch_op."""
        return self._client(name).call(
            "get_rows", name, np.asarray(rows, dtype=np.int64))

    def barrier(self, known_round=None):
        """Wait until the round this client's sends joined has fully
        applied (reference send_barrier_op). known_round: None (use the
        rounds recorded by send_grad since the last barrier — the normal
        send/barrier/recv flow), an int, or a dict endpoint->round. Runs on
        the dedicated barrier channel so it can't block pushes sharing the
        endpoint."""
        rounds = self._send_round if known_round is None else known_round
        done = {}
        for ep in set(self._assignment.values()):
            r = rounds.get(ep) if isinstance(rounds, dict) else rounds
            done[ep] = get_client(ep, channel="barrier").call("barrier", r)
        if known_round is None:
            self._send_round = {}
        return done

    def pull_all(self, scope=None) -> Dict[str, np.ndarray]:
        """Fetch every assigned param; writes into `scope` when given
        (the reference recv+concat step after the barrier)."""
        out = {}
        for name in self._assignment:
            out[name] = self.get_param(name)
            if scope is not None:
                import jax.numpy as jnp

                scope.set_var(name, jnp.asarray(out[name]))
        return out


_clients: Dict[Tuple[str, str], RpcClient] = {}
_clients_mu = threading.Lock()


def get_client(endpoint: str, channel: str = "data") -> RpcClient:
    """Process-wide client cache, one connection per (endpoint, channel)
    (the reference's grpc channel cache). Blocking calls (barrier) use
    their own channel so they can't starve data-plane pushes that share
    the endpoint."""
    with _clients_mu:
        key = (endpoint, channel)
        c = _clients.get(key)
        if c is None:
            c = _clients[key] = RpcClient(endpoint)
        return c
