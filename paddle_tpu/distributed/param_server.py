"""Executable parameter server — cross-process async/sync SGD.

The reference's pserver is `listen_and_serv_op.cc:78-192`: block on N
gradient sends, run per-param optimize sub-blocks via an Executor, handle
sparse SelectedRows grads, answer parameter gets. This is that capability
around OUR stack: an RPC service (distributed/rpc.py framing) wrapping an
Executor that runs the per-param slices of
`DistributeTranspiler.get_pserver_program(ep)`.

  - push_grad(name, grad[, trainer_id]) — grad is dense ndarray OR
    SelectedRows (rows/value/height ride the wire, the row-wise lazy
    optimizer ops apply them without densifying). async mode applies
    immediately (reference sync_mode=False); sync mode accumulates until
    all `trainers` have pushed, sums (dense add / SelectedRows concat —
    reference listen_and_serv_op.cc:181-192), applies once, and releases
    the barrier.
  - get_param(name) — current value from the pserver scope.
  - barrier() — sync mode: wait until the current round's updates applied
    (the reference's send_barrier_op).

Trainer side: `ParameterClient` (send/recv), or in-graph `send`/`recv`
ops the Executor runs as host ops (reference send_op.cc/recv_op.cc) —
see DistributeTranspiler.get_trainer_program(send_recv=True).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger
from .rpc import RpcClient, RpcServer

__all__ = ["ParameterServer", "ParameterClient", "get_client"]

_log = get_logger("pserver")

# ISSUE 1 instrumentation: push/pull volume counters plus the sync-mode
# barrier wait-time histogram — the number that shows straggler trainers
# (a fat p99 here IS the straggler, before anyone reads a timeline)
_m_push = _metrics.counter("pserver.push_grad")
_m_get = _metrics.counter("pserver.get_param")
_m_get_rows = _metrics.counter("pserver.get_rows")
_m_barrier_ms = _metrics.histogram("pserver.barrier_wait_ms")
# ISSUE 2: trainers whose heartbeat lease lapsed and were dropped from
# the sync barrier — each eviction is a round that DEGRADED instead of
# deadlocking
_m_evicted = _metrics.counter("pserver.evicted_trainers")


class ParameterServer:
    """Runs the optimize slice of a pserver program behind RPC."""

    def __init__(self, pserver_program, startup_program=None,
                 trainers: int = 1, sync_mode: bool = False, scope=None,
                 heartbeat_timeout: Optional[float] = None,
                 barrier_timeout: float = 120.0):
        """startup_program initializes a fresh scope; alternatively pass an
        already-populated `scope` (the ListenAndServ in-process form, where
        the server shares the builder's state).

        `heartbeat_timeout`: failure-detection lease in seconds. When set,
        a sync-mode trainer that has made contact (a push or a heartbeat
        RPC) and then goes silent for longer than this is EVICTED from the
        barrier: the round completes over the surviving trainers instead
        of deadlocking on the dead one. None (default) keeps the classic
        behavior — barrier waits the full `barrier_timeout`, then raises."""
        import paddle_tpu.fluid as fluid

        if startup_program is None and scope is None:
            raise ValueError("need startup_program or a populated scope")
        self._trainers = max(1, int(trainers))
        self._sync = bool(sync_mode)
        self._hb_timeout = (None if heartbeat_timeout is None
                            else float(heartbeat_timeout))
        self._barrier_timeout = float(barrier_timeout)
        # failure detection: trainer_id -> last-contact monotonic time
        # (pushes piggyback a beat; ParameterClient can also run a
        # dedicated heartbeat thread), plus the evicted set. Guarded by
        # the big _cv lock like the rest of the sync bookkeeping.
        self._beats: Dict[int, float] = {}  # guarded-by: _cv
        self._evicted: set = set()  # guarded-by: _cv
        # trainer_id -> lifetime eviction count, echoed in barrier
        # replies so the EVICTED side learns its round was degraded (it
        # otherwise sees a successful barrier and never knows its
        # in-flight pushes were withdrawn)
        self._evict_count: Dict[int, int] = {}  # guarded-by: _cv
        self._scope = scope if scope is not None else fluid.Scope()
        self._exe = fluid.Executor()
        self._program = pserver_program
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._round = 0  # guarded-by: _cv
        # sync: param -> {trainer_id: grad} — DISTINCT trainers complete a
        # round (a retransmitted push overwrites, it can't phantom-complete)
        self._pending: Dict[str, Dict[int, Any]] = {}  # guarded-by: _cv
        self._applied_round: set = set()  # guarded-by: _cv
        self._steps = 0  # guarded-by: _shared_mu
        # async: one lock per param (concurrent cross-param applies);
        # _shared_mu guards the cycle bookkeeping + counters, and
        # _shared_run_mu serializes the stateful LR-chain executions
        self._param_locks: Dict[str, threading.Lock] = {}
        self._shared_mu = threading.Lock()
        self._shared_run_mu = threading.Lock()
        # params applied since the shared (LR-decay) program last ran: the
        # shared chain advances once per DISTINCT-PARAM CYCLE — a repeat
        # push means a new optimization step started — not once per
        # len(owned) raw pushes, which drifts when a sparse workload skips
        # params in a step (ADVICE r3)
        self._applied_since_shared: set = set()  # guarded-by: _shared_mu

        block = pserver_program.global_block()
        self._owned = sorted(
            n for n, v in block.vars.items()
            if getattr(v.desc, "is_parameter", False)
        )
        self._param_locks = {p: threading.Lock() for p in self._owned}
        owned = set(self._owned)
        # Split the pserver program (reference listen_and_serv: per-param
        # optimize sub-blocks + ONE lr-decay sub-block run once per round):
        #  - shared STATEFUL ops (advance persistable non-param state, e.g.
        #    the LR-decay step counter) run once per round, not once per
        #    param-push — otherwise a 2-param pserver would decay the LR
        #    twice per step;
        #  - everything else shared (stateless arithmetic) stays in each
        #    per-param slice, where recomputing it is free.
        persistable = {n for n, v in block.vars.items() if v.persistable}
        shared_stateful = []
        for op in block.ops:
            outs = set(op.desc.output_names())
            if not (outs & owned) and (outs & (persistable - owned)):
                shared_stateful.append(op)
        shared_idx = {id(op) for op in shared_stateful}

        def _slice(keep_pred):
            prog = pserver_program.clone()
            b = prog.global_block()
            keep = [op for orig, op in zip(block.ops, b.ops)
                    if keep_pred(orig)]
            b.ops = keep
            used = set(owned)
            for op in keep:
                used.update(n for n in op.desc.input_names() if n)
                used.update(n for n in op.desc.output_names() if n)
            b.vars = {n: v for n, v in b.vars.items() if n in used}
            prog._bump_version()
            return prog

        self._shared_prog = None
        if shared_stateful:
            self._shared_prog = _slice(lambda op: id(op) in shared_idx)
        self._per_param: Dict[str, Any] = {}
        self._grad_name: Dict[str, str] = {}
        for p in self._owned:
            def keep(op, p=p):
                outs = set(op.desc.output_names())
                if id(op) in shared_idx:
                    return False
                return p in outs or not (outs & owned)

            self._per_param[p] = _slice(keep)
            # the grad feed name is whatever the optimize op's Grad input
            # actually is (clipping/regularization can rename it)
            gname = p + "@GRAD"
            for op in block.ops:
                if p in set(op.desc.output_names()):
                    g = (op.desc.inputs.get("Grad") or [gname])[0]
                    gname = g or gname
                    break
            self._grad_name[p] = gname

        if startup_program is not None:
            with fluid.scope_guard(self._scope):
                self._exe.run(startup_program)

        # traffic evidence for the sparse-prefetch contract (the round-3
        # verdict's acceptance test asserts trainer traffic is proportional
        # to batch ids, not table size); incremented from concurrent
        # handler threads, so guarded by their own lock
        self._stats_mu = threading.Lock()
        self._full_pull_rows = 0  # guarded-by: _stats_mu
        self._prefetch_rows = 0  # guarded-by: _stats_mu

        self._server = RpcServer({
            "get_param": self.get_param,
            "get_rows": self.get_rows,
            "push_grad": self.push_grad,
            "barrier": self.barrier,
            "heartbeat": self.heartbeat,
            "owned_params": self.owned_params,
            "stats": self.stats,
        }, idempotent={
            # reads + beats: re-execution on retransmit is harmless, and
            # keeping their (large, for get_param) responses OUT of the
            # dedup cache bounds its memory to small push/barrier acks
            "get_param", "get_rows", "owned_params", "stats", "heartbeat",
        })

    # --- RPC methods ---------------------------------------------------
    def owned_params(self) -> List[str]:
        return list(self._owned)

    def stats(self) -> Dict[str, int]:
        """Evidence of server-side work: optimize steps applied + round +
        rows served via full pulls vs row-granular prefetches. Each field
        is read under ITS guard (sequentially, never nested): _evicted /
        _round under _cv (barrier threads mutate them concurrently, and
        iterating a set mid-mutation raises), _steps under _shared_mu
        (concurrent _apply threads increment it there), the pull-row
        tallies under _stats_mu (guards-lint finding: they used to be
        read under _cv while handler threads wrote them under
        _stats_mu)."""
        with self._shared_mu:
            steps = self._steps
        with self._stats_mu:
            full_pull_rows = self._full_pull_rows
            prefetch_rows = self._prefetch_rows
        with self._cv:
            return {"steps": steps, "round": self._round,
                    "sync": self._sync, "trainers": self._trainers,
                    "evicted": sorted(self._evicted),
                    "full_pull_rows": full_pull_rows,
                    "prefetch_rows": prefetch_rows}

    def heartbeat(self, trainer_id: int = 0):
        """Failure-detection beat (reference go/pserver etcd TTL-lease
        keepalive). Refreshes the trainer's lease; deliberately does NOT
        resurrect an evicted trainer — only a fresh push_grad (evidence
        of forward progress) rejoins it, so a paused process whose
        heartbeat thread wakes first can't re-wedge the barrier it was
        evicted from. The reply tells the trainer its own standing."""
        with self._cv:
            tid = int(trainer_id)
            if self._hb_timeout is not None and tid not in self._evicted:
                self._beats[tid] = time.monotonic()
            return {"round": self._round, "evicted": tid in self._evicted}

    def get_param(self, name: str):
        if name not in self._owned:
            raise KeyError(f"param '{name}' is not owned by this pserver")
        _m_get.inc()
        v = self._scope.find_var(name)
        arr = np.asarray(v)
        with self._stats_mu:
            self._full_pull_rows += int(arr.shape[0]) if arr.ndim else 1
        return arr

    def get_rows(self, name: str, rows):
        """Row-granular pull: only the requested embedding rows ride the
        wire (reference prefetch_op.cc + the distributed-lookup-table
        design doc — the capability that lets a vocab far larger than one
        trainer's memory train efficiently)."""
        if name not in self._owned:
            raise KeyError(f"param '{name}' is not owned by this pserver")
        _m_get_rows.inc()
        rows = np.asarray(rows, dtype=np.int64).reshape(-1)
        table = np.asarray(self._scope.find_var(name))
        if rows.size and (rows.min() < 0 or rows.max() >= table.shape[0]):
            raise IndexError(
                f"prefetch rows out of range for '{name}' "
                f"[0, {table.shape[0]})"
            )
        with self._stats_mu:
            self._prefetch_rows += int(rows.size)
        return table[rows]

    def push_grad(self, name: str, grad, trainer_id: int = 0):
        if name not in self._owned:
            raise KeyError(f"param '{name}' is not owned by this pserver")
        _m_push.inc()
        if not self._sync:
            # hogwild-style async with PER-PARAM atomicity: updates to one
            # param serialize (an unserialized read-modify-write would drop
            # whole gradients), while different params apply CONCURRENTLY
            # from different handler threads — the reference pserver's
            # per-block locking (parameter_server2's block-sharded applies)
            with self._param_locks[name]:
                self._apply(name, grad)
            # monitoring echo, read under each field's own guard (the
            # guards lint caught the bare reads racing concurrent applies)
            with self._shared_mu:
                step = self._steps
            with self._cv:
                rnd = self._round
            return {"step": step, "round": rnd}
        with self._cv:
            tid = int(trainer_id)
            self._note_push_locked(tid)
            # the round this grad belongs to, BEFORE any completion this
            # push might trigger — the trainer barriers on it (its whole
            # step's pushes share it: a round cannot complete without this
            # trainer's last push, so it can't advance mid-step)
            round_of_push = self._round
            self._pending.setdefault(name, {})[tid] = grad
            self._try_complete_locked(name)
        # step echo read under ITS guard (_shared_mu), after _cv released
        with self._shared_mu:
            step = self._steps
        return {"step": step, "round": round_of_push}

    def barrier(self, known_round: Optional[int] = None,
                trainer_id: Optional[int] = None):
        """Sync mode: block until round `known_round` (the value push_grad
        returned for this trainer's sends) has completed (reference
        send_barrier_op: send, barrier, recv). Waiting on a round NUMBER —
        not on queue emptiness — keeps a fast trainer's next-round pushes
        from wedging a slow trainer's barrier. known_round=None just
        reports the current round.

        With heartbeat_timeout set, the wait loop doubles as the failure
        detector: each wake-up evicts trainers whose lease lapsed, which
        can complete the round over the survivors — one dead trainer
        degrades the round instead of deadlocking it. `trainer_id` names
        the CALLER so its own lease refreshes while it is parked here (a
        waiting trainer is alive by definition)."""
        if not self._sync or known_round is None:
            with self._cv:  # _round is _cv-guarded state
                return {"round": self._round}
        target = int(known_round) + 1
        t0 = time.perf_counter()
        deadline = time.monotonic() + self._barrier_timeout
        # wake often enough to evict promptly; without heartbeats one
        # full-length wait preserves the classic single-sleep behavior
        step = (self._barrier_timeout if self._hb_timeout is None
                else max(0.05, self._hb_timeout / 4.0))
        with self._cv, _tracing.span("pserver.barrier", round=target):
            while self._round < target:
                if (trainer_id is not None and self._hb_timeout is not None
                        and int(trainer_id) not in self._evicted):
                    self._beats[int(trainer_id)] = time.monotonic()
                self._evict_dead_locked()
                if self._round >= target:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _m_barrier_ms.observe((time.perf_counter() - t0) * 1e3)
                    raise TimeoutError(
                        f"sync round {known_round} incomplete after "
                        f"{self._barrier_timeout:.0f}s — a trainer died "
                        f"mid-round (pending: {list(self._pending)})")
                self._cv.wait(min(step, remaining))
            _m_barrier_ms.observe((time.perf_counter() - t0) * 1e3)
            out = {"round": self._round}
            if trainer_id is not None:
                out["evictions"] = self._evict_count.get(int(trainer_id), 0)
            return out

    # --- failure detection (all under the _cv lock) ----------------------
    def _live_count_locked(self) -> int:
        return max(1, self._trainers - len(self._evicted))

    def _note_push_locked(self, tid: int):
        """A push is evidence of forward progress: refresh the lease AND
        rejoin an evicted trainer (elastic restart — its resumed step's
        grads count toward rounds again)."""
        if self._hb_timeout is None:
            return
        self._beats[tid] = time.monotonic()
        if tid in self._evicted:
            self._evicted.discard(tid)
            _log.warning("pserver: trainer %d rejoined after eviction "
                         "(round %d)", tid, self._round)

    def _evict_dead_locked(self) -> bool:
        """Drop trainers whose heartbeat lease lapsed, withdraw their
        partial-round pushes (they belong to a step the trainer never
        finished), and re-check round completion at the reduced quorum.
        Only trainers that made contact at least once are evictable —
        the detector can't distinguish 'never started' from 'dead', and
        startup must not race the lease."""
        if self._hb_timeout is None:
            return False
        now = time.monotonic()
        newly = [tid for tid, t in self._beats.items()
                 if tid not in self._evicted and now - t > self._hb_timeout]
        if not newly:
            return False
        for tid in newly:
            self._evicted.add(tid)
            self._evict_count[tid] = self._evict_count.get(tid, 0) + 1
            _m_evicted.inc()
            _log.warning(
                "pserver: evicting trainer %d — no heartbeat for %.2fs "
                "(lease %.2fs); round %d degrades to %d live trainers",
                tid, now - self._beats[tid], self._hb_timeout, self._round,
                self._live_count_locked())
            for d in self._pending.values():
                d.pop(tid, None)
        self._try_complete_locked()
        return True

    def _try_complete_locked(self, name: Optional[str] = None):
        """Apply every pending param whose DISTINCT live pushes reach the
        live-trainer quorum; advance the round when every owned param has
        applied (an empty pending map alone is not enough — params not
        yet pushed this round leave it empty too)."""
        live = self._live_count_locked()
        for n in ([name] if name is not None else list(self._pending)):
            d = self._pending.get(n)
            if d and len(d) >= live:
                merged = _merge_grads(list(self._pending.pop(n).values()))
                self._apply(n, merged)
                self._applied_round.add(n)
        if self._applied_round >= set(self._owned):
            self._applied_round.clear()
            self._round += 1
            self._cv.notify_all()

    # --- internals -----------------------------------------------------
    def _apply(self, name: str, grad):
        """Caller holds this param's lock (async) or the big cv lock
        (sync). Cross-param concurrency is safe: per-param programs write
        disjoint scope names; the shared LR chain's cycle bookkeeping and
        its stateful execution take their own locks (an apply may read an
        LR mid-decay of a concurrent cycle boundary — the documented
        hogwild staleness, not a lost update)."""
        import paddle_tpu.fluid as fluid

        with fluid.scope_guard(self._scope):
            # shared stateful chain (LR-decay counters) advances once per
            # distinct-param cycle: at the first push ever, and whenever a
            # param REPEATS (its second push means a new step began)
            run_shared = False
            with self._shared_mu:
                if name in self._applied_since_shared or \
                        not self._applied_since_shared:
                    run_shared = self._shared_prog is not None
                    self._applied_since_shared = set()
                self._applied_since_shared.add(name)
            if run_shared:
                with self._shared_run_mu:
                    self._exe.run(self._shared_prog)
            with _tracing.span("pserver.apply", param=name):
                self._exe.run(self._per_param[name],
                              feed={self._grad_name[name]: grad})
        with self._shared_mu:
            self._steps += 1

    # --- introspection (ISSUE 3) ----------------------------------------
    def _debug_status(self) -> Dict[str, Any]:
        """The /statusz view: param table, round/step progress, and the
        failure detector's live heartbeat ages — the page an operator
        reads to tell a straggler from a dead trainer without attaching
        a debugger. Under the _cv lock like stats()."""
        now = time.monotonic()
        params = {}
        for p in self._owned:
            v = self._scope.find_var(p)
            arr = np.asarray(v) if v is not None else None
            params[p] = ({"shape": list(arr.shape), "dtype": str(arr.dtype)}
                         if arr is not None else None)
        with self._shared_mu:  # _steps is _shared_mu state, like stats()
            steps = self._steps
        with self._cv:
            beats = {str(tid): round(now - t, 3)
                     for tid, t in self._beats.items()}
            out = {
                "sync": self._sync,
                "trainers": self._trainers,
                "round": self._round,
                "steps": steps,
                "heartbeat_timeout_s": self._hb_timeout,
                "heartbeat_age_s": beats,
                "evicted": sorted(self._evicted),
                "pending_params": {n: sorted(d)
                                   for n, d in self._pending.items()},
            }
        out["params"] = params
        out["rpc"] = self._server.stats()  # dedup-cache occupancy
        return out

    # --- lifecycle -----------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0
              ) -> Tuple[str, int]:
        addr = self._server.serve(host, port)
        if _tracing.process_label() is None:
            _tracing.set_process_label(f"pserver:{addr[1]}")
        # PADDLE_TPU_DEBUG_PORT attaches the process-shared debug HTTP
        # server; this pserver's state appears under /statusz
        from ..observability import debug_server as _dbg

        self._debug_key = f"pserver:{addr[1]}"
        if _dbg.maybe_serve_from_env() is not None:
            _dbg.add_status(self._debug_key, self._debug_status)
        return addr

    @property
    def address(self):
        return self._server.address

    def shutdown(self):
        from ..observability import debug_server as _dbg

        _dbg.remove_status(getattr(self, "_debug_key", None))
        self._server.shutdown()


def _merge_grads(grads: List[Any]):
    """Sum a sync round's gradients (reference listen_and_serv_op.cc
    :181-192: dense sum / SelectedRows concat-then-merge)."""
    from ..fluid.selected_rows import SelectedRows, is_selected_rows

    if any(is_selected_rows(g) for g in grads):
        rows = np.concatenate([np.asarray(g.rows) for g in grads])
        value = np.concatenate([np.asarray(g.value) for g in grads])
        return SelectedRows(rows, value, grads[0].height)
    out = np.asarray(grads[0])
    for g in grads[1:]:
        out = out + np.asarray(g)
    return out


class ParameterClient:
    """Trainer-side client (reference operators/detail/grpc_client.cc +
    send_op/recv_op): push grads to / pull params from the pserver that
    owns each variable."""

    def __init__(self, assignment: Dict[str, str], trainer_id: int = 0,
                 heartbeat_interval: Optional[float] = None):
        """assignment: param name -> "host:port" endpoint
        (DistributeTranspiler.param_assignment).

        `heartbeat_interval`: when set, a daemon thread beats every
        assigned pserver this often so the server's failure detector can
        tell 'slow' from 'dead' (set it well under the server's
        heartbeat_timeout — a third is the usual lease ratio). Without
        it, pushes still piggyback a beat, so a trainer that dies
        between steps is detected either way."""
        self._assignment = dict(assignment)
        self._trainer_id = int(trainer_id)
        # endpoint -> round of this step's first send, consumed by barrier()
        self._send_round: Dict[str, int] = {}
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if heartbeat_interval:
            self._hb_interval = float(heartbeat_interval)
            # dedicated FAIL-FAST clients, one per endpoint: a beat must
            # never queue behind a large push on the shared data
            # connection, and — since the loop visits endpoints
            # sequentially — a single dead pserver must not hold the
            # thread through a long timeout/retry budget while HEALTHY
            # pservers miss this trainer's beats and falsely evict it.
            # The next interval is the retry.
            self._hb_clients = {
                ep: RpcClient(ep, timeout=max(1.0, 2 * self._hb_interval),
                              retries=0)
                for ep in set(self._assignment.values())}
            self._hb_thread = threading.Thread(
                target=self._beat_loop, daemon=True,
                name=f"pserver-heartbeat-t{self._trainer_id}")
            self._hb_thread.start()

    def _beat_loop(self):
        while not self._hb_stop.wait(self._hb_interval):
            for c in self._hb_clients.values():
                try:
                    c.call("heartbeat", self._trainer_id)
                except Exception:
                    pass  # an unreachable pserver must not kill the beat

    def stop_heartbeat(self):
        """Stop beating (tests use this to simulate a silent death)."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
            for c in self._hb_clients.values():
                c.close()

    def close(self):
        self.stop_heartbeat()

    def _client(self, name: str) -> RpcClient:
        ep = self._assignment.get(name)
        if ep is None:
            raise KeyError(f"no pserver assignment for '{name}'")
        return get_client(ep)

    def send_grad(self, name: str, grad):
        resp = self._client(name).call("push_grad", name, grad,
                                       self._trainer_id)
        ep = self._assignment[name]
        if isinstance(resp, dict) and ep not in self._send_round:
            # remember which round this step's pushes joined, so a bare
            # barrier() can wait on the right round number
            self._send_round[ep] = resp.get("round")
        return resp

    def get_param(self, name: str) -> np.ndarray:
        """Zero-copy pull: the result is a READ-ONLY view over the RPC
        frame's bytes (copy_result=False) — get_param is an idempotent
        read whose result feeds device transfer/math; the old
        per-segment receive copy was pure overhead on the largest
        tensors the wire carries. Callers needing in-place mutation
        must .copy() (numpy raises on write, so misuse is loud)."""
        return self._client(name).call("get_param", name,
                                       copy_result=False)

    def get_rows(self, name: str, rows) -> np.ndarray:
        """Pull only the given rows of a (large) table — the trainer-side
        half of the reference's prefetch_op. Read-only zero-copy view,
        like get_param."""
        return self._client(name).call(
            "get_rows", name, np.asarray(rows, dtype=np.int64),
            copy_result=False)

    def barrier(self, known_round=None):
        """Wait until the round this client's sends joined has fully
        applied (reference send_barrier_op). known_round: None (use the
        rounds recorded by send_grad since the last barrier — the normal
        send/barrier/recv flow), an int, or a dict endpoint->round. Runs on
        the dedicated barrier channel so it can't block pushes sharing the
        endpoint."""
        rounds = self._send_round if known_round is None else known_round
        done = {}
        for ep in set(self._assignment.values()):
            r = rounds.get(ep) if isinstance(rounds, dict) else rounds
            # per-trainer channel: two in-process trainers must not
            # serialize their (long) barrier waits on one shared
            # connection; trainer_id rides along so the server refreshes
            # the caller's heartbeat lease while it is parked
            done[ep] = get_client(
                ep, channel=f"barrier.{self._trainer_id}").call(
                    "barrier", r, self._trainer_id)
            note_barrier_reply(ep, self._trainer_id, done[ep])
        if known_round is None:
            self._send_round = {}
        return done

    def pull_all(self, scope=None) -> Dict[str, np.ndarray]:
        """Fetch every assigned param; writes into `scope` when given
        (the reference recv+concat step after the barrier)."""
        out = {}
        for name in self._assignment:
            out[name] = self.get_param(name)
            if scope is not None:
                import jax.numpy as jnp

                scope.set_var(name, jnp.asarray(out[name]))
        return out


_eviction_seen: Dict[Tuple[str, int], int] = {}
_eviction_seen_mu = threading.Lock()


def note_barrier_reply(endpoint: str, trainer_id: int, resp) -> bool:
    """Client-side eviction detector, shared by ParameterClient.barrier
    and the executor's send_barrier host op: a growing `evictions` count
    in the barrier reply means THIS trainer was declared dead mid-round
    and its in-flight pushes were withdrawn — a successful-looking
    barrier that silently degraded the round. Warn loudly (the fix is a
    heartbeat_timeout above worst-case step time), return True if a new
    eviction was seen."""
    if not isinstance(resp, dict) or "evictions" not in resp:
        return False
    key = (endpoint, int(trainer_id))
    with _eviction_seen_mu:
        prev = _eviction_seen.get(key, 0)
        cur = int(resp["evictions"])
        _eviction_seen[key] = cur
    if cur > prev:
        _log.warning(
            "trainer %d was EVICTED by pserver %s %d time(s) since last "
            "seen: a round completed without this trainer's gradients "
            "(step time likely exceeded the server's heartbeat_timeout "
            "— raise it above the worst-case step, or beat via "
            "ParameterClient(heartbeat_interval=...))",
            trainer_id, endpoint, cur - prev)
        return True
    return False


_clients: Dict[Tuple[str, str], RpcClient] = {}
_clients_mu = threading.Lock()

# barrier channels wait for a whole sync round to complete server-side:
# their socket timeout must comfortably exceed ANY configurable server
# barrier_timeout, or a legitimately slow round reads as a dead
# connection and the healthy trainer dies retrying (a truly dead server
# still surfaces instantly as a connection reset, not a timeout)
BARRIER_CLIENT_TIMEOUT = 3600.0


def get_client(endpoint: str, channel: str = "data",
               timeout: Optional[float] = None) -> RpcClient:
    """Process-wide client cache, one connection per (endpoint, channel)
    (the reference's grpc channel cache). Blocking calls (barrier) use
    their own channel so they can't starve data-plane pushes that share
    the endpoint. `timeout` applies only when the channel's client is
    first created; barrier channels default to BARRIER_CLIENT_TIMEOUT."""
    with _clients_mu:
        key = (endpoint, channel)
        c = _clients.get(key)
        if c is None:
            kw = {}
            if channel.startswith("barrier"):
                # long reads (a whole slow round), but: fast dial (the
                # default connect_timeout), and a single reconnect —
                # retrying a barrier that already waited out a long
                # timeout is useless (that round is ancient history)
                kw = {"timeout": (BARRIER_CLIENT_TIMEOUT
                                  if timeout is None else timeout),
                      "retries": 1}
            elif timeout is not None:
                kw = {"timeout": timeout}
            c = _clients[key] = RpcClient(endpoint, **kw)
        return c
