"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
early-2018 PaddlePaddle (reference: zhye5230/Paddle), redesigned for JAX/XLA.

Architecture (vs the reference):
  - The reference builds a protobuf ProgramDesc from Python and interprets it
    op-by-op with a C++ Executor dispatching CUDA kernels
    (reference: paddle/fluid/framework/executor.cc:133).
  - Here the same Program IR is built from Python, but the Executor is a
    *compiler client*: each block is lowered to ONE XLA computation via JAX
    tracing of per-op emitters, jit-compiled and cached, with all state
    (parameters, optimizer accumulators, BN stats) resident in device HBM.
  - Multi-device data/model parallelism is expressed with jax.sharding over a
    device Mesh; XLA inserts ICI collectives where the reference inserted
    NCCLAllReduceOpHandle (reference:
    paddle/fluid/framework/details/multi_devices_graph_builder.cc:167).
"""

__version__ = "0.1.0"

from . import observability  # noqa: F401  (no heavy deps; before fluid)
from . import fluid  # noqa: F401
from . import dataset, reader  # noqa: F401
from .reader import batch  # noqa: F401

# PADDLE_TPU_SANITIZE=guards: instrument the guarded-by-annotated runtime
# classes so every declared-guard access asserts its lock is held (the
# dynamic half of the analysis/guards.py lint). Zero import cost unset.
if fluid.flags.FLAGS["sanitize"]:
    from .analysis import sanitize as _sanitize

    _sanitize.maybe_install()
