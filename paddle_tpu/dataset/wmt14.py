"""WMT14 fr-en (reference python/paddle/dataset/wmt14.py): (src_ids,
trg_ids, trg_next_ids) triples. Serves the REAL wmt14.tgz wire format —
a tarball holding `src.dict` / `trg.dict` (one token per line, line
number = id) and tab-separated "src sentence\\ttrg sentence" pair files
under train/ and test/ (reference wmt14.py:52 __read_to_dict, :78
reader_creator) — when it sits under `data_home()/wmt14/`; else a
synthetic fallback with copy-task structure so seq2seq models can
learn."""
from __future__ import annotations

import os
import tarfile

import numpy as np

from . import common

DICT_SIZE = 30000
START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID = 0
END_ID = 1
UNK_ID = 2
TAR_NAME = "wmt14.tgz"
MAX_LEN = 80  # reference drops training pairs longer than this


def _tar_path():
    return os.path.join(common.data_home(), "wmt14", TAR_NAME)


def _load_dict(tarf, suffix: str, dict_size: int):
    names = [m.name for m in tarf if m.name.endswith(suffix)]
    assert len(names) == 1, (suffix, names)
    out = {}
    for i, line in enumerate(tarf.extractfile(names[0])):
        if i >= dict_size:
            break
        out[line.decode("utf-8").strip()] = i
    return out


# (tar mtime, dict_size) -> (src_dict, trg_dict): the dictionaries are
# re-used every epoch AND by get_dict — parse the tarball once, not per
# reader() call (imdb.build_dict memoizes for the same reason)
_dict_cache: dict = {}


def _load_dicts(dict_size: int):
    key = (os.path.getmtime(_tar_path()), dict_size)
    if key not in _dict_cache:
        with tarfile.open(_tar_path(), mode="r") as f:
            _dict_cache[key] = (_load_dict(f, "src.dict", dict_size),
                                _load_dict(f, "trg.dict", dict_size))
    return _dict_cache[key]


def _real_reader(file_suffix: str, dict_size: int):
    def reader():
        src_dict, trg_dict = _load_dicts(dict_size)
        with tarfile.open(_tar_path(), mode="r") as f:
            names = [m.name for m in f
                     if file_suffix in m.name and m.isfile()
                     and not m.name.endswith(".dict")]
            for name in sorted(names):
                for line in f.extractfile(name):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    trg_words = parts[1].split()
                    src_ids = [src_dict.get(w, UNK_ID)
                               for w in [START] + src_words + [END]]
                    trg_ids = [trg_dict.get(w, UNK_ID) for w in trg_words]
                    if len(src_ids) > MAX_LEN or len(trg_ids) > MAX_LEN:
                        continue
                    trg_next = trg_ids + [trg_dict[END]]
                    trg_ids = [trg_dict[START]] + trg_ids
                    yield src_ids, trg_ids, trg_next

    return reader


def _reader_creator(split: str, dict_size: int):
    if os.path.exists(_tar_path()):
        return _real_reader("train/" if split == "train" else "test/",
                            dict_size)

    def reader():
        g = common.rng("wmt14", split)
        for _ in range(512):
            length = int(g.integers(3, 30))
            src = g.integers(3, dict_size, size=length).tolist()
            trg = src[::-1]  # reversal copy-task
            yield src, [START_ID] + trg, trg + [END_ID]

    return reader


def train(dict_size=DICT_SIZE):
    return _reader_creator("train", dict_size)


def test(dict_size=DICT_SIZE):
    return _reader_creator("test", dict_size)


def get_dict(dict_size=DICT_SIZE, reverse=False):
    """(src_dict, trg_dict); reverse=True returns id->word maps
    (reference wmt14.py:136)."""
    if os.path.exists(_tar_path()):
        src, trg = _load_dicts(dict_size)
        if reverse:
            return ({v: k for k, v in src.items()},
                    {v: k for k, v in trg.items()})
        return src, trg
    src = {i: f"w{i}" for i in range(dict_size)}
    return (src, src) if reverse else (
        {v: k for k, v in src.items()}, {v: k for k, v in src.items()}
    )
