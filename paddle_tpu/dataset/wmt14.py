"""WMT14 fr-en (reference python/paddle/dataset/wmt14.py): (src_ids,
trg_ids, trg_next_ids) triples. Synthetic fallback with copy-task structure
so seq2seq models can learn."""
from __future__ import annotations

import numpy as np

from . import common

DICT_SIZE = 30000
START_ID = 0
END_ID = 1
UNK_ID = 2


def _reader_creator(split: str, dict_size: int):
    def reader():
        g = common.rng("wmt14", split)
        for _ in range(512):
            length = int(g.integers(3, 30))
            src = g.integers(3, dict_size, size=length).tolist()
            trg = src[::-1]  # reversal copy-task
            yield src, [START_ID] + trg, trg + [END_ID]

    return reader


def train(dict_size=DICT_SIZE):
    return _reader_creator("train", dict_size)


def test(dict_size=DICT_SIZE):
    return _reader_creator("test", dict_size)


def get_dict(dict_size=DICT_SIZE, reverse=False):
    src = {i: f"w{i}" for i in range(dict_size)}
    return (src, src) if reverse else (
        {v: k for k, v in src.items()}, {v: k for k, v in src.items()}
    )
