"""PASCAL VOC2012 segmentation (reference python/paddle/dataset/voc2012.py):
(image [3,H,W] float32, segmentation label [H,W] int32). Synthetic 64x64
fallback: labels are thresholded channel blobs so a seg net can learn."""
from __future__ import annotations

import numpy as np

from . import common

NUM_CLASSES = 21
H = W = 64


def _reader_creator(split: str):
    def reader():
        g = common.rng("voc2012", split)
        for _ in range(64):
            img = g.random((3, H, W), dtype=np.float32)
            cls = int(g.integers(1, NUM_CLASSES))
            mask = (img.mean(axis=0) > 0.5)
            label = np.where(mask, cls, 0).astype(np.int32)
            yield img, label

    return reader


def train():
    return _reader_creator("train")


def test():
    return _reader_creator("test")


def val():
    return _reader_creator("val")
