"""CoNLL-2005 semantic role labeling (reference
python/paddle/dataset/conll05.py): per-token 8 feature slots + BIO label.
Synthetic fallback with predicate-correlated labels so the SRL book model
(label_semantic_roles) can learn."""
from __future__ import annotations

from . import common

WORD_DICT_LEN = 44068
LABEL_DICT_LEN = 67
PRED_DICT_LEN = 3162


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_DICT_LEN)}
    verb_dict = {f"v{i}": i for i in range(PRED_DICT_LEN)}
    label_dict = {f"l{i}": i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Reference returns a pretrained word-embedding file path; synthetic
    data has none."""
    return None


def _reader_creator(split: str):
    def reader():
        g = common.rng("conll05", split)
        for _ in range(256):
            length = int(g.integers(5, 40))
            word = g.integers(0, WORD_DICT_LEN, size=length).tolist()
            pred = int(g.integers(0, PRED_DICT_LEN))
            mark_pos = int(g.integers(0, length))
            mark = [1 if i == mark_pos else 0 for i in range(length)]
            # labels correlated with distance to the predicate: learnable
            label = [
                (abs(i - mark_pos) + pred) % LABEL_DICT_LEN
                for i in range(length)
            ]
            ctx = [
                [(w + d) % WORD_DICT_LEN for w in word]
                for d in (-2, -1, 0, 1, 2)
            ]
            yield (word, ctx[0], ctx[1], ctx[2], ctx[3], ctx[4],
                   [pred] * length, mark, label)

    return reader


def train():
    return _reader_creator("train")


def test():
    return _reader_creator("test")
