"""WMT16 en-de (reference python/paddle/dataset/wmt16.py): (src_ids,
trg_in_ids, trg_out_ids) with configurable vocab sizes and <s>/<e>/<unk>
specials. Synthetic copy-task fallback like wmt14."""
from __future__ import annotations

from . import common

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220
START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2


def get_dict(lang: str, dict_size: int, reverse: bool = False):
    words = [START_MARK, END_MARK, UNK_MARK] + [
        f"{lang}{i}" for i in range(dict_size - 3)
    ]
    if reverse:
        return {i: w for i, w in enumerate(words)}
    return {w: i for i, w in enumerate(words)}


def _reader_creator(split: str, src_dict_size: int, trg_dict_size: int):
    def reader():
        g = common.rng("wmt16", split)
        for _ in range(512):
            length = int(g.integers(3, 30))
            src = g.integers(3, src_dict_size, size=length).tolist()
            trg = [t % (trg_dict_size - 3) + 3 for t in src[::-1]]
            yield src, [START_ID] + trg, trg + [END_ID]

    return reader


def train(src_dict_size=TOTAL_EN_WORDS, trg_dict_size=TOTAL_DE_WORDS,
          src_lang="en"):
    return _reader_creator("train", src_dict_size, trg_dict_size)


def test(src_dict_size=TOTAL_EN_WORDS, trg_dict_size=TOTAL_DE_WORDS,
         src_lang="en"):
    return _reader_creator("test", src_dict_size, trg_dict_size)


def validation(src_dict_size=TOTAL_EN_WORDS, trg_dict_size=TOTAL_DE_WORDS,
               src_lang="en"):
    return _reader_creator("val", src_dict_size, trg_dict_size)
