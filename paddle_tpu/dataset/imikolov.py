"""PTB-style n-gram language-model dataset (reference
python/paddle/dataset/imikolov.py): yields (w0..w_{n-2}, w_{n-1}) id tuples.
Synthetic fallback: a noisy deterministic word chain so an n-gram model has
real signal to learn."""
from __future__ import annotations

import numpy as np

from . import common

WORD_DICT_SIZE = 200


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(WORD_DICT_SIZE)}


def _reader_creator(split: str, n: int):
    def reader():
        g = common.rng("imikolov", split)
        v = WORD_DICT_SIZE
        n_seqs = 256
        for _ in range(n_seqs):
            length = 24
            w = int(g.integers(0, v))
            seq = [w]
            for _ in range(length - 1):
                if g.random() < 0.85:
                    w = (w * 3 + 7) % v
                else:
                    w = int(g.integers(0, v))
                seq.append(w)
            for i in range(len(seq) - n + 1):
                yield tuple(seq[i:i + n])

    return reader


def train(word_idx=None, n=5):
    return _reader_creator("train", n)


def test(word_idx=None, n=5):
    return _reader_creator("test", n)
