"""IMDB sentiment (reference python/paddle/dataset/imdb.py): sequences of
word ids + binary label. Serves the REAL aclImdb_v1.tar.gz wire format
(members aclImdb/{train,test}/{pos,neg}/*.txt, ad-hoc tokenization:
punctuation stripped, lowercased, whitespace split; dict built by
descending frequency then lexical order, '<unk>' appended — reference
imdb.py:36 tokenize / :55 build_dict) when the tarball sits under
`data_home()/imdb/`; else a synthetic fallback with class-correlated
ids."""
from __future__ import annotations

import os
import re
import string
import tarfile

import numpy as np

from . import common

VOCAB_SIZE = 5147
TAR_NAME = "aclImdb_v1.tar.gz"

_PUNCT_TABLE = str.maketrans("", "", string.punctuation)


def _tar_path():
    return os.path.join(common.data_home(), "imdb", TAR_NAME)


def _tokenize(pattern: "re.Pattern"):
    """Yield the token list of every tar member matching `pattern`.
    Sequential tarfile iteration, matching the reference's note about
    member order; tokenization = strip punctuation, lower, split."""
    with tarfile.open(_tar_path()) as tarf:
        tf = tarf.next()
        while tf is not None:
            if tf.isfile() and pattern.match(tf.name):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="replace")
                yield (text.rstrip("\n\r").translate(_PUNCT_TABLE)
                       .lower().split())
            tf = tarf.next()


_DICT_CACHE: dict = {}


def build_dict(pattern=None, cutoff=0):
    """Word -> id by descending frequency (ties: lexical), '<unk>' last
    (reference imdb.py:55). Default pattern covers the whole train split.
    Memoized per (tar path, mtime, pattern, cutoff): on the real 80 MB
    tarball one build is a full decompress+tokenize pass — train() and
    test() must not each redo it."""
    if pattern is None:
        pattern = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
    path = _tar_path()
    key = (path, os.path.getmtime(path), pattern.pattern, cutoff)
    cached = _DICT_CACHE.get(key)
    if cached is not None:
        return dict(cached)
    freq: dict = {}
    for doc in _tokenize(pattern):
        for w in doc:
            freq[w] = freq.get(w, 0) + 1
    kept = [(w, c) for w, c in freq.items() if c > cutoff]
    kept.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    _DICT_CACHE[key] = dict(word_idx)
    return word_idx


def word_dict():
    if os.path.exists(_tar_path()):
        return build_dict()
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def _real_reader(split: str, word_idx: dict):
    unk = word_idx["<unk>"]

    def load(polarity, label):
        pat = re.compile(rf"aclImdb/{split}/{polarity}/.*\.txt$")
        for doc in _tokenize(pat):
            yield [word_idx.get(w, unk) for w in doc], label

    def reader():
        # reference reader_creator: positives labelled 0, negatives 1
        yield from load("pos", 0)
        yield from load("neg", 1)

    return reader


def _reader_creator(split: str, word_idx=None):
    if os.path.exists(_tar_path()):
        return _real_reader(split, word_idx or word_dict())

    def reader():
        g = common.rng("imdb", split)
        n = 512
        for _ in range(n):
            label = int(g.integers(0, 2))
            length = int(g.integers(8, 120))
            base = g.integers(0, VOCAB_SIZE, size=length)
            if label == 1:
                base[: length // 3] = base[: length // 3] % 100
            else:
                base[: length // 3] = 100 + base[: length // 3] % 100
            yield base.tolist(), label

    return reader


def train(word_idx=None):
    return _reader_creator("train", word_idx)


def test(word_idx=None):
    return _reader_creator("test", word_idx)
