"""IMDB sentiment (reference python/paddle/dataset/imdb.py): sequences of
word ids + binary label. Synthetic fallback with class-correlated ids."""
from __future__ import annotations

import numpy as np

from . import common

VOCAB_SIZE = 5147


def word_dict():
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def _reader_creator(split: str):
    def reader():
        g = common.rng("imdb", split)
        n = 512
        for _ in range(n):
            label = int(g.integers(0, 2))
            length = int(g.integers(8, 120))
            base = g.integers(0, VOCAB_SIZE, size=length)
            if label == 1:
                base[: length // 3] = base[: length // 3] % 100
            else:
                base[: length // 3] = 100 + base[: length // 3] % 100
            yield base.tolist(), label

    return reader


def train(word_idx=None):
    return _reader_creator("train")


def test(word_idx=None):
    return _reader_creator("test")
