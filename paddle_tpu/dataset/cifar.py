"""CIFAR-10/100 (reference python/paddle/dataset/cifar.py). Serves the
REAL wire format when the original tarballs sit under
`data_home()/cifar/` — a .tar.gz of python-pickled batch dicts
({'data': uint8 [N, 3072], 'labels' or 'fine_labels': [N]}, py2 pickles,
so keys decode as BYTES under encoding='bytes') — else a synthetic
fallback: [3072] floats in [0,1], labels with a planted channel-mean
signal."""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from . import common

CIFAR10_TAR = "cifar-10-python.tar.gz"
CIFAR100_TAR = "cifar-100-python.tar.gz"


def _real_reader(tar_path: str, sub_name: str):
    """Stream every batch member whose name contains `sub_name`
    (reference cifar.py:47 reader_creator): unpickle, yield
    (pixels/255 float32 [3072], int label). `fine_labels` carries the
    CIFAR-100 class."""

    def reader():
        with tarfile.open(tar_path, mode="r") as f:
            names = sorted(m.name for m in f
                           if sub_name in m.name and m.isfile())
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                data = batch.get(b"data", batch.get("data"))
                labels = batch.get(b"labels", batch.get("labels"))
                if labels is None:
                    labels = batch.get(b"fine_labels",
                                       batch.get("fine_labels"))
                assert data is not None and labels is not None, name
                data = np.asarray(data, dtype=np.uint8)
                for sample, label in zip(data, labels):
                    yield (sample / 255.0).astype(np.float32), int(label)

    return reader


def _reader_creator(split: str, num_classes: int):
    tar_name = CIFAR10_TAR if num_classes == 10 else CIFAR100_TAR
    tar_path = os.path.join(common.data_home(), "cifar", tar_name)
    if os.path.exists(tar_path):
        if num_classes == 10:
            # cifar-10 batches: data_batch_1..5 / test_batch
            sub = "data_batch" if split == "train" else "test_batch"
        else:
            # cifar-100: single 'train' / 'test' members
            sub = split
        return _real_reader(tar_path, sub)

    def reader():
        g = common.rng(f"cifar{num_classes}", split)
        n = 1024
        images = g.random((n, 3 * 32 * 32), dtype=np.float32)
        labels = g.integers(0, num_classes, size=n)
        images[np.arange(n), labels % 3072] += 0.5
        for i in range(n):
            yield images[i], int(labels[i])

    return reader


def train10():
    return _reader_creator("train", 10)


def test10():
    return _reader_creator("test", 10)


def train100():
    return _reader_creator("train", 100)


def test100():
    return _reader_creator("test", 100)
