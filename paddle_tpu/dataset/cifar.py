"""CIFAR-10/100 (reference python/paddle/dataset/cifar.py). Synthetic
fallback: [3072] floats in [0,1], labels with a planted channel-mean signal."""
from __future__ import annotations

import numpy as np

from . import common


def _reader_creator(split: str, num_classes: int):
    def reader():
        g = common.rng(f"cifar{num_classes}", split)
        n = 1024
        images = g.random((n, 3 * 32 * 32), dtype=np.float32)
        labels = g.integers(0, num_classes, size=n)
        images[np.arange(n), labels % 3072] += 0.5
        for i in range(n):
            yield images[i], int(labels[i])

    return reader


def train10():
    return _reader_creator("train", 10)


def test10():
    return _reader_creator("test", 10)


def train100():
    return _reader_creator("train", 100)


def test100():
    return _reader_creator("test", 100)
