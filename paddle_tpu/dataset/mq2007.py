"""MQ2007 learning-to-rank (reference python/paddle/dataset/mq2007.py):
query-grouped 46-dim feature vectors with relevance labels, in pointwise /
pairwise / listwise forms. Synthetic fallback: relevance = noisy linear
function of the features so rankers can learn."""
from __future__ import annotations

import numpy as np

from . import common

FEATURE_DIM = 46


def _make_query(g):
    n_docs = int(g.integers(5, 20))
    feats = g.random((n_docs, FEATURE_DIM), dtype=np.float32)
    w = np.linspace(1.0, 0.1, FEATURE_DIM, dtype=np.float32)
    score = feats @ w + g.normal(0, 0.1, size=n_docs)
    spread = score.max() - score.min()
    rel = np.clip((score - score.min()) / (spread + 1e-6) * 2.99, 0,
                  2).astype(np.int64)
    return rel, feats


def _reader_creator(split: str, format: str):
    def reader():
        g = common.rng("mq2007", split)
        for _ in range(128):
            rel, feats = _make_query(g)
            if format == "listwise":
                yield rel.tolist(), feats
            elif format == "pairwise":
                order = np.argsort(-rel)
                for i in range(len(order)):
                    for j in range(i + 1, len(order)):
                        if rel[order[i]] > rel[order[j]]:
                            yield feats[order[i]], feats[order[j]]
            else:  # pointwise
                for r, f in zip(rel, feats):
                    yield f, int(r)

    return reader


def train(format: str = "pairwise"):
    return _reader_creator("train", format)


def test(format: str = "pairwise"):
    return _reader_creator("test", format)
