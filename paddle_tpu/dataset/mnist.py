"""MNIST reader creators (reference python/paddle/dataset/mnist.py).

Looks for the standard IDX files under `common.DATA_HOME/mnist`; otherwise
serves deterministic synthetic digits with the real shapes ([784] floats in
[-1,1], int label 0-9)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

TRAIN_SIZE = 60000
TEST_SIZE = 10000


def _load_idx(images_path, labels_path):
    with gzip.open(labels_path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(images_path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows * cols)
    return images, labels


def _reader_creator(split: str, limit: int):
    data_dir = os.path.join(common.data_home(), "mnist")
    prefix = "train" if split == "train" else "t10k"
    images_path = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte.gz")
    labels_path = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte.gz")

    if os.path.exists(images_path) and os.path.exists(labels_path):
        def reader():
            images, labels = _load_idx(images_path, labels_path)
            for i in range(images.shape[0]):
                yield (images[i].astype(np.float32) / 127.5 - 1.0,
                       int(labels[i]))

        return reader

    def synthetic_reader():
        g = common.rng("mnist", split)
        n = min(limit, 2048)
        images = g.standard_normal((n, 784)).astype(np.float32).clip(-1, 1)
        labels = g.integers(0, 10, size=n)
        # embed a weak class signal so models can actually learn
        for i in range(n):
            images[i, labels[i] * 78:(labels[i] + 1) * 78] += 1.5
        for i in range(n):
            yield images[i], int(labels[i])

    return synthetic_reader


def train():
    return _reader_creator("train", TRAIN_SIZE)


def test():
    return _reader_creator("test", TEST_SIZE)
