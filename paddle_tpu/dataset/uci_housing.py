"""UCI housing (reference python/paddle/dataset/uci_housing.py): 13 features,
1 regression target. Synthetic linear-plus-noise fallback so fit_a_line has a
learnable signal."""
from __future__ import annotations

import numpy as np

from . import common

FEATURE_DIM = 13


def _make(split: str, n: int):
    g = common.rng("uci_housing", "shared")
    w = g.standard_normal(FEATURE_DIM).astype(np.float32)
    b = 2.0
    gs = common.rng("uci_housing", split)
    x = gs.standard_normal((n, FEATURE_DIM)).astype(np.float32)
    y = x @ w + b + 0.1 * gs.standard_normal(n).astype(np.float32)
    return x, y.astype(np.float32)


def train():
    def reader():
        x, y = _make("train", 404)
        for i in range(x.shape[0]):
            yield x[i], y[i:i + 1]

    return reader


def test():
    def reader():
        x, y = _make("test", 102)
        for i in range(x.shape[0]):
            yield x[i], y[i:i + 1]

    return reader
