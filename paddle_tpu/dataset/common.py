"""Shared dataset helpers (reference python/paddle/dataset/common.py, minus
the downloader — no egress; synthetic fallbacks are deterministic)."""
from __future__ import annotations

import os

import numpy as np

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA", os.path.expanduser("~/.cache/paddle_tpu/dataset")
)


def rng(name: str, split: str) -> np.random.Generator:
    seed = abs(hash((name, split))) % (2**31)
    return np.random.default_rng(seed)
