"""Shared dataset helpers (reference python/paddle/dataset/common.py, minus
the downloader — no egress; synthetic fallbacks are deterministic)."""
from __future__ import annotations

import os
import zlib

import numpy as np

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA", os.path.expanduser("~/.cache/paddle_tpu/dataset")
)


def data_home() -> str:
    """Resolve the data root at CALL time (not import time) so a test or
    launcher can point PADDLE_TPU_DATA at real-format files after the
    package is already imported."""
    return os.environ.get("PADDLE_TPU_DATA", DATA_HOME)


def rng(name: str, split: str) -> np.random.Generator:
    # crc32, not hash(): python's hash is salted per process, which would
    # make "deterministic" synthetic data differ between processes
    seed = zlib.crc32(f"{name}/{split}".encode()) % (2**31)
    return np.random.default_rng(seed)


# the files each reader requires before it serves real data (must match
# the reader's own probe — a PARTIAL drop still serves synthetic, and this
# report must say so)
_REQUIRED_FILES = {
    "mnist": ["train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
              "t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"],
    # 'cifar' covers the CIFAR-10 readers; CIFAR-100 provenance must be
    # queried with the explicit file (data_source("cifar",
    # "cifar-100-python.tar.gz")) since either tarball can be dropped
    # without the other
    "cifar": ["cifar-10-python.tar.gz"],
    "imdb": ["aclImdb_v1.tar.gz"],
    "wmt14": ["wmt14.tgz"],
}


def data_source(name: str, *relative_files: str) -> str:
    """'real' when every file the reader needs exists under
    DATA_HOME/<name>, else 'synthetic' — so experiments can STATE which
    data trained them (book chapters in hermetic CI run on the synthetic
    fallbacks; drop the original files under DATA_HOME to switch every
    reader to real data). Pass the file list explicitly for datasets not
    in _REQUIRED_FILES; a bare name with no known file list conservatively
    reports 'synthetic' rather than guessing from a non-empty directory."""
    base = os.path.join(data_home(), name)
    files = list(relative_files) or _REQUIRED_FILES.get(name)
    if not files:
        return "synthetic"
    ok = all(os.path.exists(os.path.join(base, f)) for f in files)
    return "real" if ok else "synthetic"
