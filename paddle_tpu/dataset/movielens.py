"""MovieLens (reference python/paddle/dataset/movielens.py). Synthetic
fallback with the reference's slot structure for the recommender book test."""
from __future__ import annotations

import numpy as np

from . import common

USER_COUNT = 944
MOVIE_COUNT = 1683
JOB_COUNT = 21
AGE_COUNT = 7
CATEGORY_COUNT = 18
TITLE_VOCAB = 5175


def max_user_id():
    return USER_COUNT - 1


def max_movie_id():
    return MOVIE_COUNT - 1


def max_job_id():
    return JOB_COUNT - 1


def age_table():
    return list(range(AGE_COUNT))


def movie_categories():
    return {f"c{i}": i for i in range(CATEGORY_COUNT)}


def get_movie_title_dict():
    return {f"t{i}": i for i in range(TITLE_VOCAB)}


def _reader_creator(split: str):
    def reader():
        g = common.rng("movielens", split)
        for _ in range(512):
            user_id = int(g.integers(1, USER_COUNT))
            gender = int(g.integers(0, 2))
            age = int(g.integers(0, AGE_COUNT))
            job = int(g.integers(0, JOB_COUNT))
            movie_id = int(g.integers(1, MOVIE_COUNT))
            categories = g.integers(0, CATEGORY_COUNT,
                                    size=int(g.integers(1, 4))).tolist()
            title = g.integers(0, TITLE_VOCAB,
                               size=int(g.integers(2, 8))).tolist()
            score = float(g.integers(1, 6))
            yield [user_id, gender, age, job, movie_id, categories, title, score]

    return reader


def train():
    return _reader_creator("train")


def test():
    return _reader_creator("test")
