"""Datasets (reference python/paddle/dataset/ — 14 auto-downloading sets).

This environment has no network egress, so each dataset module provides the
same reader-creator API backed by deterministic synthetic data with the real
shapes/vocab sizes; pass `data_dir`/env PADDLE_TPU_DATA to use real data laid
out on disk where available.
"""
from . import cifar, flowers, imdb, imikolov, mnist, movielens, uci_housing, wmt14  # noqa: F401

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "flowers", "movielens", "wmt14"]
