"""Datasets (reference python/paddle/dataset/ — 14 auto-downloading sets).

This environment has no network egress, so each dataset module provides the
same reader-creator API backed by deterministic synthetic data with the real
shapes/vocab sizes; pass `data_dir`/env PADDLE_TPU_DATA to use real data laid
out on disk where available.
"""
from . import (  # noqa: F401
    cifar,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = [
    "mnist", "cifar", "uci_housing", "imdb", "imikolov", "flowers",
    "movielens", "wmt14", "wmt16", "conll05", "sentiment", "voc2012",
    "mq2007",
]
