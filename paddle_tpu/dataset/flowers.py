"""102-flowers (reference python/paddle/dataset/flowers.py): 3x224x224 images,
102 classes. Synthetic fallback."""
from __future__ import annotations

import numpy as np

from . import common


def _reader_creator(split: str):
    def reader():
        g = common.rng("flowers", split)
        for _ in range(256):
            img = g.random((3, 224, 224), dtype=np.float32)
            label = int(g.integers(0, 102))
            yield img, label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader_creator("train")


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader_creator("test")


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader_creator("valid")
