"""NLTK movie-review sentiment (reference python/paddle/dataset/sentiment.py):
(word id sequence, 0/1 label). Synthetic fallback, class-correlated ids."""
from __future__ import annotations

from . import common

VOCAB_SIZE = 2048


def get_word_dict():
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def _reader_creator(split: str):
    def reader():
        g = common.rng("sentiment", split)
        for _ in range(400):
            label = int(g.integers(0, 2))
            length = int(g.integers(10, 80))
            ids = g.integers(0, VOCAB_SIZE, size=length)
            ids[::4] = (ids[::4] % 200) + label * 200
            yield ids.tolist(), label

    return reader


def train():
    return _reader_creator("train")


def test():
    return _reader_creator("test")
