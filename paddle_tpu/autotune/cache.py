"""Tuning cache — measured knobs replace hand-set constants (ISSUE 8).

Every performance knob in the framework used to be a constant read off
one bench session on one device kind (`flash_min_seq=3072` from a v5e
table, `serving_buckets` 1/2/4/8/16 regardless of traffic). TVM
(PAPERS.md) is the blueprint this subsystem follows: decisions come
from a persistent tuning log over measured/modeled candidates, and the
hard-coded values survive only as *cold-cache defaults*.

The cache is a three-level map::

    (device_kind, tunable_id, shape_key) -> record

  - ``device_kind`` — ``jax.devices()[0].device_kind`` normalized
    (``cpu``, ``tpu_v5_lite``, ...). Ragged Paged Attention (PAPERS.md)
    motivates the keying: the kernel-vs-reference crossover is a
    property of the CHIP, not of the code, so one cache file can carry
    per-device-kind routing for a heterogeneous fleet.
  - ``tunable_id`` — the knob's name (``flash_min_seq``,
    ``paged_min_slots``, ``serving_buckets``, ``executor.step``, ...).
  - ``shape_key`` — ``""`` for shape-independent knobs, a stable
    shape/program fingerprint for per-shape records (step timings),
    ``"ladder"`` for derived bucket ladders.

Records are either decisions (``{"value": ..., "source": "measured" |
"model" | "derived" | "override"}``) or timing logs (``{"n",
"median_ms", "best_ms", "samples_ms"}``) — see measure.py for who
writes which.

Persistence: when a directory is configured (``PADDLE_TPU_AUTOTUNE_DIR``
/ ``FLAGS['autotune_dir']``) the cache serializes to
``tuning_cache.json`` with the same torn-write discipline as
``master.snapshot``: full tmp write + fsync, then an atomic
``os.replace`` (the ``autotune.save`` fault site sits between them, so
chaos tests can prove a crash mid-save never corrupts the previous
file). A corrupt or unreadable file degrades to an EMPTY cache — every
consumer then falls back to its hand-set default, which is exactly the
pre-autotune behavior (``autotune.cache.corrupt`` counts the event).

Every ``lookup`` counts ``autotune.cache.hits`` / ``autotune.cache.
misses`` — the counter pair that PROVES routing reads through the
cache (the ISSUE 8 acceptance test asserts on it).
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
from typing import Any, Dict, Optional

from ..observability import metrics as _metrics
from ..observability.log import get_logger

__all__ = ["TuningCache", "device_kind", "get_cache", "reset_cache",
           "scoped", "tuned_value", "CACHE_FILENAME"]

_log = get_logger("autotune")

_m_hits = _metrics.counter("autotune.cache.hits")
_m_misses = _metrics.counter("autotune.cache.misses")
_m_stores = _metrics.counter("autotune.cache.stores")
_m_corrupt = _metrics.counter("autotune.cache.corrupt")

CACHE_FILENAME = "tuning_cache.json"
_SCHEMA = 1
# per-key timing log depth: enough for a stable median, bounded so a
# long training session cannot grow the cache file per step
_TIMING_SAMPLES = 16


def _median(vals) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


_kind_mu = threading.Lock()
_device_kind: Optional[str] = None  # guarded-by: _kind_mu


def device_kind() -> str:
    """Normalized device kind of the default jax backend (``cpu``,
    ``tpu_v5_lite``, ...) — the first key of every cache entry.
    Computed once per process (the backend cannot change under us)."""
    global _device_kind
    with _kind_mu:
        if _device_kind is None:
            try:
                import jax

                kind = str(jax.devices()[0].device_kind)
            except Exception:  # no backend: still usable as a dumb store
                kind = "unknown"
            _device_kind = "_".join(
                "".join(c if c.isalnum() else " " for c in kind.lower())
                .split()) or "unknown"
        return _device_kind


class TuningCache:
    """The persistent (device_kind, tunable_id, shape_key) -> record
    store. Thread-safe: serving schedulers, executors, and benches all
    read/write it concurrently."""

    def __init__(self, dirname: Optional[str] = None):
        self._mu = threading.Lock()
        # serializes whole flushes (snapshot -> tmp write -> rename):
        # without it a SLOW flusher could os.replace a stale payload
        # over a newer flusher's file after the newer generation's
        # dirty bit was already cleared — a silently lost decision
        self._flush_mu = threading.Lock()
        # never rebound after construction (safe to read lock-free)
        self.dirname = str(dirname) if dirname else None
        self._data: Dict[str, Dict[str, Dict[str, Dict[str, Any]]]] = \
            {}  # guarded-by: _mu
        self._dirty = False  # guarded-by: _mu
        # bumped on every mutation: flush() re-validates it before
        # clearing _dirty, so a put() landing mid-write is never lost
        self._gen = 0  # guarded-by: _mu
        if self.dirname:
            self._load()

    # -- persistence ------------------------------------------------------
    @property
    def path(self) -> Optional[str]:
        return (os.path.join(self.dirname, CACHE_FILENAME)
                if self.dirname else None)

    def _load(self):
        """Read the cache file; ANY corruption degrades to empty (=
        hand-set defaults everywhere), never an error at import/load."""
        path = self.path
        if not path or not os.path.exists(path):
            return
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc, dict) or doc.get("schema") != _SCHEMA:
                raise ValueError(f"bad schema: {type(doc).__name__}")
            entries = doc["entries"]
            for dev, per_dev in entries.items():
                for tid, per_tid in per_dev.items():
                    for sk, rec in per_tid.items():
                        if not isinstance(rec, dict):
                            raise ValueError(f"non-dict record at "
                                             f"{dev}/{tid}/{sk}")
        except Exception as e:
            _m_corrupt.inc()
            _log.warning(
                "tuning cache %s is corrupt (%s: %s) — degrading to "
                "defaults (an empty cache); the next flush rewrites it",
                path, type(e).__name__, e)
            return
        with self._mu:
            self._data = entries

    def flush(self) -> Optional[str]:
        """Persist atomically (tmp + fsync + rename, the master.snapshot
        discipline). Returns the path written, or None (no directory /
        nothing dirty). A crash between tmp-write and rename — the
        ``autotune.save`` fault site — leaves the previous file intact
        and the cache still dirty, so a retry re-writes everything."""
        from ..distributed import faults as _faults

        with self._flush_mu:  # one flusher at a time, snapshot->rename
            with self._mu:
                if not self.dirname or not self._dirty:
                    return None
                gen = self._gen
                payload = json.dumps(
                    {"schema": _SCHEMA, "entries": self._data},
                    indent=1, sort_keys=True)
            os.makedirs(self.dirname, exist_ok=True)
            path = self.path
            # unique per writer: belt-and-braces under _flush_mu, and a
            # crashed flush's abandoned tmp never collides with a retry
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            _faults.fire("autotune.save")
            os.replace(tmp, path)
            # the check-then-act window is re-validated inside the
            # second acquisition: only the generation that was
            # serialized is marked clean — a mutation that landed
            # mid-write keeps the cache dirty
            # lint: allow-unguarded(_dirty)
            with self._mu:
                if self._gen == gen:
                    self._dirty = False
        return path

    # -- records ----------------------------------------------------------
    def lookup(self, tunable_id: str, shape_key: str = "",
               default: Any = None, device: Optional[str] = None,
               count: bool = True) -> Any:
        """The decision read-through: the cached value for this device
        kind, or ``default`` (the hand-set constant). Counts
        ``autotune.cache.hits``/``misses``."""
        dev = device or device_kind()
        with self._mu:
            rec = self._data.get(dev, {}).get(
                str(tunable_id), {}).get(str(shape_key))
        if rec is None or "value" not in rec:
            if count:
                _m_misses.inc()
            return default
        if count:
            _m_hits.inc()
        return rec["value"]

    def put(self, tunable_id: str, value: Any, shape_key: str = "",
            source: str = "measured", device: Optional[str] = None,
            extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Store a decision. ``source`` records provenance: 'measured'
        (timed runs), 'model' (XLA cost_analysis), 'derived' (ladder
        from a shape histogram), 'override' (an operator pin)."""
        dev = device or device_kind()
        rec: Dict[str, Any] = {"value": value, "source": str(source)}
        if extra:
            rec.update(extra)
        with self._mu:
            self._data.setdefault(dev, {}).setdefault(
                str(tunable_id), {})[str(shape_key)] = rec
            self._dirty = True
            self._gen += 1
        _m_stores.inc()
        return rec

    def note_timing(self, tunable_id: str, shape_key: str, ms: float,
                    device: Optional[str] = None):
        """Append one timing sample for a (tunable, shape) key — the
        executor's per-shape step log. Bounded (last _TIMING_SAMPLES
        samples; count/min exact), so per-step calls cannot grow the
        cache."""
        dev = device or device_kind()
        ms = float(ms)
        with self._mu:
            rec = self._data.setdefault(dev, {}).setdefault(
                str(tunable_id), {}).setdefault(str(shape_key), {})
            samples = rec.setdefault("samples_ms", [])
            samples.append(round(ms, 4))
            del samples[:-_TIMING_SAMPLES]
            rec["n"] = int(rec.get("n", 0)) + 1
            rec["median_ms"] = round(_median(samples), 4)
            rec["best_ms"] = round(min(ms, float(rec.get("best_ms", ms))), 4)
            self._dirty = True
            self._gen += 1

    def timing(self, tunable_id: str, shape_key: str = "",
               device: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """The timing record for a key, or None — a present record is
        how repeat sessions skip re-measurement."""
        dev = device or device_kind()
        with self._mu:
            rec = self._data.get(dev, {}).get(
                str(tunable_id), {}).get(str(shape_key))
            return dict(rec) if rec and "n" in rec else None

    def entries(self) -> Dict[str, Any]:
        """Deep snapshot of every record (bench evidence / --dump)."""
        with self._mu:
            return json.loads(json.dumps(self._data))

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            n = sum(len(per_tid)
                    for per_dev in self._data.values()
                    for per_tid in per_dev.values())
            return {"dirname": self.dirname, "device_kinds":
                    sorted(self._data), "entries": n}

    def clear(self):
        with self._mu:
            self._data = {}
            self._dirty = True
            self._gen += 1


# -- the process singleton -----------------------------------------------

_cache_mu = threading.Lock()
_cache: Optional[TuningCache] = None  # guarded-by: _cache_mu


def get_cache() -> TuningCache:
    """The process cache, created lazily from ``FLAGS['autotune_dir']``
    (itself seeded from ``PADDLE_TPU_AUTOTUNE_DIR``). The directory is
    read ONCE at creation — use ``scoped()`` (tests/benches) or
    ``reset_cache()`` to re-point it."""
    global _cache
    with _cache_mu:
        if _cache is None:
            from ..fluid.flags import FLAGS

            _cache = TuningCache(FLAGS["autotune_dir"] or None)
        return _cache


def reset_cache():
    """Drop the singleton; the next get_cache() re-reads the flag."""
    global _cache
    with _cache_mu:
        _cache = None


@contextlib.contextmanager
def scoped(dirname: Optional[str] = None, enable: bool = True):
    """Swap in a fresh cache — and flip ``FLAGS['autotune']`` — for a
    with-block, restoring both on exit (the test/selftest harness,
    mirroring ``faults.scoped``). Yields the scoped TuningCache."""
    from ..fluid.flags import FLAGS

    global _cache
    fresh = TuningCache(dirname)
    with _cache_mu:
        prev = _cache
        _cache = fresh
    prev_flag, prev_dir = FLAGS["autotune"], FLAGS["autotune_dir"]
    FLAGS["autotune"] = bool(enable)
    FLAGS["autotune_dir"] = dirname or ""
    try:
        yield fresh
    finally:
        FLAGS["autotune"] = prev_flag
        FLAGS["autotune_dir"] = prev_dir
        # restoring the pre-block snapshot IS the contract: the scoped
        # cache is discarded wholesale, like faults.scoped's plan swap
        # lint: allow-unguarded(_cache)
        with _cache_mu:
            _cache = prev


def tuned_value(tunable_id: str, default: Any = None,
                shape_key: str = "", device: Optional[str] = None,
                count: bool = True) -> Any:
    """Routing read-through on the singleton (see
    ``fluid.flags.effective_flag``): cached decision for this device
    kind, else the hand-set default. ``count=False`` for bookkeeping
    reads (jit-key construction) that must not inflate the
    routing-proof hit/miss counters."""
    return get_cache().lookup(tunable_id, shape_key=shape_key,
                              default=default, device=device, count=count)


def _atexit_flush():  # pragma: no cover - exercised via subprocess runs
    with _cache_mu:
        c = _cache
    if c is not None:
        try:
            c.flush()
        except Exception as e:
            _log.warning("tuning-cache atexit flush failed: %s: %s",
                         type(e).__name__, e)


atexit.register(_atexit_flush)
