"""Shape-histogram recorder + bucket-ladder derivation (ISSUE 8).

The serving engines pad every batch up to a fixed bucket ladder so the
jit cache stays bounded (serving/engine.py). The ladder SHAPE is a pure
trade: more buckets = less padding waste but more warm-time compiles;
bucket POSITIONS decide how much of each padded batch is waste. The
static default (1/2/4/8/16) is right only for traffic that happens to
be geometric — real request-size distributions are lumpy, and the
right ladder is a function of the observed distribution.

This module closes that loop:

  - ``observe(tunable_id, value)`` — the recorder. The serving submit
    paths call it with every real request's row count (and the decode
    path with its slot demand), so any running session — including a
    bench — accumulates the traffic histogram the tuner needs. One
    dict increment under a lock, metrics-cheap, always on.
  - ``derive_ladder(hist, max_buckets, coverage)`` — a PURE function
    (property-tested): exact DP over the observed sizes minimizing
    ``expected_padding_waste``, the mean per-request padding fraction
    — the same quantity the ``serving.padding_waste`` histogram
    measures with one-request batches. Sizes above the ``coverage``
    (default P99) quantile don't get to spend optimization buckets —
    the top bucket still covers the max observed size, so nothing
    admissible today becomes inadmissible under a derived ladder.
  - ``resolve_ladder(tunable_id, default)`` — what ``buckets="auto"``
    / ``slots="auto"`` call at engine LOAD: a cached derived ladder
    for this device kind wins, else derive from the live histogram
    (enough observations), else the static default. Resolution happens
    once, before ``warm()`` — the ladder is fixed after warm, so the
    zero-post-warm-compiles invariant is untouched.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from ..observability import metrics as _metrics

__all__ = ["ShapeHistogram", "observe", "histogram", "histograms",
           "merge_observed", "reset_histograms", "derive_ladder",
           "expected_padding_waste", "percentile_size", "resolve_ladder",
           "seed_cache_from_observed"]

_m_observed = _metrics.counter("autotune.shapes_observed")
_m_derived = _metrics.counter("autotune.ladders_derived")

# DP is O(n^2 k) in DISTINCT sizes: compress pathological histograms
# (ragged NLP lengths) down to this many quantile-thinned sizes first
_MAX_DISTINCT = 512


class ShapeHistogram:
    """Counts of one observed integer shape dimension (request rows,
    slot demand). Thread-safe: observe() is called on request submit
    paths from arbitrary client threads."""

    def __init__(self, name: str):
        self.name = str(name)
        self._mu = threading.Lock()
        self._counts: Dict[int, int] = {}  # guarded-by: _mu
        self._n = 0  # guarded-by: _mu

    def observe(self, value: int):
        v = int(value)
        if v < 1:
            return
        with self._mu:
            self._counts[v] = self._counts.get(v, 0) + 1
            self._n += 1

    def merge(self, counts: Dict[int, int]):
        """Fold a saved histogram in (seeding from a bench artifact)."""
        with self._mu:
            for v, c in counts.items():
                v, c = int(v), int(c)
                if v >= 1 and c > 0:
                    self._counts[v] = self._counts.get(v, 0) + c
                    self._n += c

    def total(self) -> int:
        with self._mu:
            return self._n

    def snapshot(self) -> Dict[int, int]:
        with self._mu:
            return dict(self._counts)

    def reset(self):
        with self._mu:
            self._counts = {}
            self._n = 0


_hist_mu = threading.Lock()
_hists: Dict[str, ShapeHistogram] = {}  # guarded-by: _hist_mu


def _hist(tunable_id: str) -> ShapeHistogram:
    with _hist_mu:
        h = _hists.get(tunable_id)
        if h is None:
            h = _hists[tunable_id] = ShapeHistogram(tunable_id)
        return h


def observe(tunable_id: str, value: int):
    """Record one observed shape for a tunable (the serving/decode
    submit hook). Cheap and always on — bench sessions double as tuner
    input without any flag flips."""
    _hist(tunable_id).observe(value)
    _m_observed.inc()


def histogram(tunable_id: str) -> Dict[int, int]:
    return _hist(tunable_id).snapshot()


def merge_observed(tunable_id: str, counts: Dict[int, int]):
    """Fold a SAVED histogram (a bench artifact's ``shape_histogram``
    entry — JSON string keys accepted) into the live recorder:
    replaying a previous session's traffic before resolving an "auto"
    ladder, without needing that session's tuning cache."""
    _hist(tunable_id).merge(counts)


def histograms() -> Dict[str, Dict[int, int]]:
    """Every recorded histogram (bench evidence embeds this)."""
    with _hist_mu:
        items = list(_hists.items())
    return {name: h.snapshot() for name, h in items if h.total()}


def reset_histograms():
    with _hist_mu:
        items = list(_hists.values())
    for h in items:
        h.reset()


# -- the pure math -------------------------------------------------------

def percentile_size(hist: Dict[int, int], q: float = 0.99) -> int:
    """Smallest size whose cumulative count reaches ``q`` of the total
    (nearest-rank, like metrics.Histogram)."""
    if not hist:
        raise ValueError("empty histogram")
    total = sum(hist.values())
    acc = 0
    for s in sorted(hist):
        acc += hist[s]
        if acc >= q * total:
            return int(s)
    return int(max(hist))


def expected_padding_waste(hist: Dict[int, int],
                           ladder: Sequence[int]) -> float:
    """Mean per-request padding fraction ``(bucket(s) - s) / bucket(s)``
    over the histogram — exactly what the ``serving.padding_waste``
    histogram records when every batch holds one request (the open-loop
    bench's configuration), so derived-vs-static claims are asserted
    against the SAME quantity the runtime measures. Sizes above the top
    bucket clamp (the engine would have refused them)."""
    from ..serving.engine import bucket_for  # the ONE ladder-lookup rule

    lad = sorted(set(int(b) for b in ladder))
    if not lad or lad[0] < 1:
        raise ValueError(f"bad ladder {ladder!r}")
    num = 0.0
    den = 0
    for s, c in hist.items():
        s, c = int(s), int(c)
        b = bucket_for(lad, s)
        num += c * (max(b - s, 0) / float(b))
        den += c
    return num / den if den else 0.0


def _compress(sizes: List[int], counts: Dict[int, int],
              cap: int) -> List[int]:
    """Quantile-thin distinct sizes to <= cap, always keeping the max
    (rounding a size UP to the next kept size only adds padding the
    derived ladder then accounts for)."""
    if len(sizes) <= cap:
        return sizes
    stride = -(-len(sizes) // cap)
    kept = sizes[stride - 1::stride]
    if kept[-1] != sizes[-1]:
        kept.append(sizes[-1])
    # fold dropped sizes' counts into the next kept size up
    folded: Dict[int, int] = {k: 0 for k in kept}
    ki = 0
    for s in sizes:
        while kept[ki] < s:
            ki += 1
        folded[kept[ki]] += counts[s]
    counts.clear()
    counts.update(folded)
    return kept


def derive_ladder(hist: Dict[int, int], max_buckets: int = 5,
                  coverage: float = 0.99) -> List[int]:
    """Optimal <= ``max_buckets`` bucket ladder for an observed size
    histogram: exact DP minimizing ``expected_padding_waste``.

    Deterministic (pure function of the histogram — two replicas
    derive the same ladder from the same traffic), covers P-``coverage``
    by construction, and waste is monotone non-increasing in
    ``max_buckets`` (the DP minimizes over every budget up to the cap).
    Sizes in the tail above the coverage quantile are excluded from the
    optimization (a single giant outlier must not spend a bucket) but
    the max observed size is still appended as the top bucket, so every
    size that was admissible stays admissible."""
    counts = {int(s): int(c) for s, c in hist.items()
              if int(s) >= 1 and int(c) > 0}
    if not counts:
        raise ValueError("cannot derive a ladder from an empty histogram")
    if not 0.0 < coverage <= 1.0:
        raise ValueError(f"coverage must be in (0, 1], got {coverage}")
    k_total = max(1, int(max_buckets))
    top = max(counts)
    p_cov = percentile_size(counts, coverage)
    tail = top > p_cov
    if tail and k_total == 1:
        # a budget of ONE bucket with a tail: the only ladder covering
        # everything is [max] — never exceed the documented bound
        return [top]
    # the tail (sizes above the coverage quantile) rides the reserved
    # top bucket; the DP spends the rest of the budget on the body
    body = {s: c for s, c in counts.items() if s <= p_cov}
    k = k_total - (1 if tail else 0)

    sizes = sorted(body)
    sizes = _compress(sizes, body, _MAX_DISTINCT)
    n = len(sizes)
    k = min(k, n)
    # prefix sums: cnt[i] = sum counts of sizes[:i]; wsum likewise of
    # count*size — cost(i, j) = padding fraction mass of sizes[i..j]
    # all padded to sizes[j], in O(1)
    cnt = [0] * (n + 1)
    wsum = [0] * (n + 1)
    for i, s in enumerate(sizes):
        cnt[i + 1] = cnt[i] + body[s]
        wsum[i + 1] = wsum[i] + body[s] * s

    def cost(i: int, j: int) -> float:
        # sum_{t=i..j} c_t * (s_j - s_t) / s_j
        c_range = cnt[j + 1] - cnt[i]
        w_range = wsum[j + 1] - wsum[i]
        return c_range - w_range / float(sizes[j])

    INF = float("inf")
    # dp[j] = min waste mass covering sizes[0..j] with the current
    # bucket budget, last bucket exactly sizes[j]; parent for rebuild
    dp = [cost(0, j) for j in range(n)]
    parent = [[-1] * n]
    best_m, best_val = 1, dp[n - 1]
    for m in range(2, k + 1):
        nxt = [INF] * n
        par = [-1] * n
        for j in range(m - 1, n):
            for i in range(m - 2, j):
                v = dp[i] + cost(i + 1, j)
                if v < nxt[j]:
                    nxt[j], par[j] = v, i
        dp = nxt
        parent.append(par)
        if dp[n - 1] < best_val - 1e-12:
            best_m, best_val = m, dp[n - 1]
    # rebuild the best_m-bucket solution
    ladder: List[int] = []
    j = n - 1
    for m in range(best_m, 0, -1):
        ladder.append(sizes[j])
        j = parent[m - 1][j]
    ladder.reverse()
    if tail:
        ladder.append(top)
    return sorted(set(ladder))


# -- resolution ----------------------------------------------------------

def resolve_ladder(tunable_id: str, default: Sequence[int],
                   max_buckets: int = 5, min_observations: int = 32,
                   cache=None) -> List[int]:
    """``buckets="auto"`` / ``slots="auto"`` resolution, at engine load:

      1. a cached derived ladder for this device kind (a previous
         session or a bench seeded it) — counted as a cache hit;
      2. else derive from the LIVE histogram when it holds at least
         ``min_observations`` shapes, store the result (source
         'derived') so the next session skips straight to (1);
      3. else the static ``default`` (the hand-set FLAGS ladder).
    """
    from .cache import get_cache

    c = cache or get_cache()
    cached = c.lookup(tunable_id, shape_key="ladder")
    if cached:
        return sorted(set(int(b) for b in cached))
    h = histogram(tunable_id)
    if sum(h.values()) >= int(min_observations):
        lad = derive_ladder(h, max_buckets=max_buckets)
        c.put(tunable_id, [int(b) for b in lad], shape_key="ladder",
              source="derived",
              extra={"observations": int(sum(h.values())),
                     "expected_waste":
                         round(expected_padding_waste(h, lad), 6)})
        _m_derived.inc()
        return lad
    return sorted(set(int(b) for b in default))


def seed_cache_from_observed(min_observations: int = 32,
                             max_buckets: int = 5, cache=None,
                             flush: bool = True) -> Dict[str, List[int]]:
    """Derive + store a ladder for every histogram with enough
    observations, then flush — run at the END of a bench session (with
    ``PADDLE_TPU_AUTOTUNE_DIR`` set) so the bench's traffic becomes the
    next serving session's ``buckets="auto"`` answer."""
    from .cache import get_cache

    c = cache or get_cache()
    out: Dict[str, List[int]] = {}
    for name, h in histograms().items():
        if sum(h.values()) < int(min_observations):
            continue
        lad = derive_ladder(h, max_buckets=max_buckets)
        c.put(name, [int(b) for b in lad], shape_key="ladder",
              source="derived",
              extra={"observations": int(sum(h.values()))})
        _m_derived.inc()
        out[name] = lad
    if flush and out:
        c.flush()
    return out
