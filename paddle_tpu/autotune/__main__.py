"""CLI driver for the autotune subsystem.

    python -m paddle_tpu.autotune --selftest
        In-process proof (no TPU, no datasets): ladder-derivation
        properties (P99 coverage, waste monotone in bucket budget,
        determinism, beats the static default on skewed traffic),
        cache round-trip through a real directory, corrupt-file
        degradation, measure-then-skip (a second session answers from
        the cache with ZERO new timed runs), the cost-model fallback,
        and per-device-kind routing read-through. Exit-nonzero on any
        failure — wired into tools/check.py.

    python -m paddle_tpu.autotune --dump
        Print the live tuning cache (FLAGS['autotune_dir'] /
        PADDLE_TPU_AUTOTUNE_DIR) and every recorded shape histogram as
        JSON — the operator's view of what the tuner knows.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _force_cpu():
    """The selftest must not require (or try to dial) a TPU: pin the jax
    platform before any backend initialization, the same way
    tests/conftest.py and the analysis CLI do."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


# --- selftest cases -----------------------------------------------------

def case_ladder_properties():
    from . import derive_ladder, expected_padding_waste, percentile_size

    hist = {1: 120, 2: 40, 3: 25, 6: 30, 7: 22, 13: 4, 16: 1}
    lad = derive_ladder(hist, max_buckets=5)
    assert lad == sorted(set(lad)) and lad[0] >= 1, lad
    assert lad[-1] >= percentile_size(hist, 0.99), (lad, hist)
    assert lad[-1] >= max(hist), "top bucket must keep the max admissible"
    assert derive_ladder(hist, max_buckets=5) == lad, "must be pure"
    wastes = [expected_padding_waste(hist, derive_ladder(hist, k))
              for k in (1, 2, 3, 4, 5, 6)]
    for a, b in zip(wastes, wastes[1:]):
        assert b <= a + 1e-12, f"waste not monotone in buckets: {wastes}"


def case_ladder_beats_static():
    from . import derive_ladder, expected_padding_waste

    # lumpy traffic the geometric default fits badly: 5s pad to 8,
    # 6s pad to 8, 3s pad to 4
    hist = {1: 50, 3: 30, 5: 60, 6: 40, 16: 2}
    static = [1, 2, 4, 8, 16]
    derived = derive_ladder(hist, max_buckets=5)
    w_static = expected_padding_waste(hist, static)
    w_derived = expected_padding_waste(hist, derived)
    assert w_derived < w_static, (w_derived, w_static, derived)


def case_cache_roundtrip():
    from . import TuningCache

    with tempfile.TemporaryDirectory() as tmp:
        c = TuningCache(tmp)
        c.put("flash_min_seq", 2048, source="measured")
        c.put("serving_buckets", [1, 3, 6], shape_key="ladder",
              source="derived")
        c.note_timing("executor.step", "abc|x:f32(4,8)", 1.5)
        c.note_timing("executor.step", "abc|x:f32(4,8)", 2.5)
        assert c.flush(), "flush must write when dirty"
        c2 = TuningCache(tmp)
        assert c2.lookup("flash_min_seq", default=-1) == 2048
        assert c2.lookup("serving_buckets", shape_key="ladder") == [1, 3, 6]
        t = c2.timing("executor.step", "abc|x:f32(4,8)")
        assert t and t["n"] == 2 and abs(t["median_ms"] - 2.0) < 1e-9, t


def case_cache_corrupt_degrades():
    from . import CACHE_FILENAME, TuningCache

    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, CACHE_FILENAME), "w") as f:
            f.write('{"schema": 1, "entries": {"cpu": ')  # torn JSON
        c = TuningCache(tmp)  # must not raise
        assert c.lookup("flash_min_seq", default=3072) == 3072
        c.put("flash_min_seq", 99)
        assert c.flush(), "a corrupt file must still be replaceable"
        assert TuningCache(tmp).lookup("flash_min_seq") == 99


def case_measure_then_skip():
    from . import TuningCache, measure_or_model
    from ..observability import metrics

    c = TuningCache()
    runs = {"a": 0, "b": 0}

    def runner(cand):
        runs[cand] += 1
        if cand == "b":  # 'b' is measurably slower
            sum(range(20000))

    best, ev = measure_or_model("toy_knob", ["a", "b"], runner=runner,
                                k=3, cache=c)
    assert best == "a" and ev["source"] == "measured", ev
    assert runs["a"] == 4 and runs["b"] == 4  # warmup + k each
    m0 = metrics.counter("autotune.measurements").value()
    best2, ev2 = measure_or_model("toy_knob", ["a", "b"], runner=runner,
                                  k=3, cache=c)
    assert best2 == "a" and ev2["source"] == "cache", ev2
    assert runs["a"] == 4 and runs["b"] == 4, "repeat must not re-run"
    assert metrics.counter("autotune.measurements").value() == m0


def case_model_fallback():
    from . import TuningCache, measure_or_model

    c = TuningCache()
    costs = {1: {"flops": 100.0, "bytes accessed": 10.0},
             2: {"flops": 10.0, "bytes accessed": 5.0}}
    best, ev = measure_or_model("toy_model_knob", [1, 2],
                                cost_fn=lambda cand: costs[cand], cache=c)
    assert best == 2 and ev["source"] == "model", ev
    assert c.lookup("toy_model_knob") == 2


def case_jit_cost_model():
    """The zero-run path end-to-end: lower real jax callables, extract
    cost_analysis via jax_compat, pick the structurally cheaper one."""
    import jax.numpy as jnp

    from . import TuningCache, jit_cost, measure_or_model

    x = jnp.ones((16, 16), jnp.float32)

    def shallow(a):
        return a @ a

    def deep(a):
        for _ in range(6):
            a = a @ a
        return a

    cost = jit_cost(shallow, x)
    assert float(cost.get("flops") or 0) > 0, cost
    best, ev = measure_or_model(
        "matmul_depth", ["shallow", "deep"],
        cost_fn=lambda cand: jit_cost(
            shallow if cand == "shallow" else deep, x),
        cache=TuningCache())
    assert best == "shallow" and ev["source"] == "model", ev


def case_routing_read_through():
    from . import device_kind, scoped
    from ..fluid.flags import FLAGS, effective_flag
    from ..observability import metrics

    hits = metrics.counter("autotune.cache.hits")
    misses = metrics.counter("autotune.cache.misses")
    with scoped(enable=True) as cache:
        m0, h0 = misses.value(), hits.value()
        # cold cache: the FLAGS constant is the default
        assert effective_flag("flash_min_seq") == FLAGS["flash_min_seq"]
        assert misses.value() == m0 + 1
        # an override for ANOTHER device kind must not apply here
        cache.put("flash_min_seq", 4096, device="some_other_chip",
                  source="override")
        assert effective_flag("flash_min_seq") == FLAGS["flash_min_seq"]
        # ... but one for THIS kind wins
        cache.put("flash_min_seq", 512, device=device_kind(),
                  source="override")
        assert effective_flag("flash_min_seq") == 512
        assert hits.value() == h0 + 1
    # autotune off: the constant again, no cache consulted
    m1 = misses.value()
    assert effective_flag("flash_min_seq") == FLAGS["flash_min_seq"]
    assert misses.value() == m1


def case_resolve_ladder_end_to_end():
    from . import (histogram, observe, reset_histograms, resolve_ladder,
                   scoped)

    with scoped(enable=True) as cache:
        reset_histograms()
        default = [1, 2, 4, 8, 16]
        # too few observations: the static default
        observe("selftest_buckets", 3)
        assert resolve_ladder("selftest_buckets", default,
                              min_observations=32) == default
        for size, count in {1: 40, 3: 25, 6: 20}.items():
            for _ in range(count):
                observe("selftest_buckets", size)
        lad = resolve_ladder("selftest_buckets", default,
                             min_observations=32)
        assert lad != default and lad[-1] == 6, lad
        # the derivation was cached: a fresh resolve with an EMPTY
        # histogram still answers the derived ladder
        reset_histograms()
        assert resolve_ladder("selftest_buckets", default,
                              min_observations=32) == lad
        assert cache.lookup("selftest_buckets", shape_key="ladder",
                            count=False) == lad
    reset_histograms()


CASES = [
    ("ladder_properties", case_ladder_properties),
    ("ladder_beats_static", case_ladder_beats_static),
    ("cache_roundtrip", case_cache_roundtrip),
    ("cache_corrupt_degrades", case_cache_corrupt_degrades),
    ("measure_then_skip", case_measure_then_skip),
    ("model_fallback", case_model_fallback),
    ("jit_cost_model", case_jit_cost_model),
    ("routing_read_through", case_routing_read_through),
    ("resolve_ladder_end_to_end", case_resolve_ladder_end_to_end),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.autotune")
    ap.add_argument("--selftest", action="store_true",
                    help="run the in-process proof suite")
    ap.add_argument("--dump", action="store_true",
                    help="print the live cache + shape histograms as "
                         "JSON")
    args = ap.parse_args(argv)

    _force_cpu()

    if args.dump:
        from . import get_cache, histograms

        cache = get_cache()
        print(json.dumps({
            "cache": cache.stats(),
            "entries": cache.entries(),
            "histograms": histograms(),
        }, indent=2, sort_keys=True))
        return 0

    if not args.selftest:
        ap.print_help()
        return 2

    failed = 0
    for name, fn in CASES:
        try:
            fn()
        except BaseException as e:
            failed += 1
            print(f"  {name}: FAILED — {type(e).__name__}: {e}")
        else:
            print(f"  {name}: ok")
    print(f"autotune selftest: {len(CASES)} cases, "
          f"{'all ok' if not failed else f'{failed} FAILED'}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
