"""paddle_tpu.autotune — cost-model-driven autotuning (ISSUE 8).

Turns the measurement substrate the framework already has (XLA
cost_analysis per compiled executable, request shapes flowing through
serving, step wall times) into DECISIONS, TVM-style (PAPERS.md):

  - a persistent **tuning cache** keyed ``(device_kind, tunable_id,
    shape_key)`` (cache.py — atomic JSON under
    ``PADDLE_TPU_AUTOTUNE_DIR``, corrupt files degrade to defaults);
  - a **measure-or-model engine** (measure.py — median-of-k timed runs
    when an executable exists, cost_analysis proxy as the zero-run
    fallback, repeat sessions answered from the cache);
  - a **shape-histogram recorder + ladder deriver** (ladder.py —
    observed request-size distributions become ``buckets="auto"`` /
    ``slots="auto"`` serving ladders that minimize expected padding
    waste).

Consumers: attention routing reads ``flash_min_seq`` and
``paged_min_slots`` through ``fluid.flags.effective_flag`` (the FLAGS
constants are the cold-cache defaults, overridden per device kind);
the serving engines resolve ``"auto"`` ladders at load; the executor
logs per-shape step timings. All of it is inert until
``FLAGS['autotune']`` is on — except the histogram recorder, which is
metrics-cheap and always on so bench sessions double as tuner input.

    python -m paddle_tpu.autotune --selftest   # in-process proof
    python -m paddle_tpu.autotune --dump       # cache + histograms

See docs/AUTOTUNE.md.
"""
from .cache import (CACHE_FILENAME, TuningCache, device_kind, get_cache,
                    reset_cache, scoped, tuned_value)
from .ladder import (ShapeHistogram, derive_ladder, expected_padding_waste,
                     histogram, histograms, merge_observed, observe,
                     percentile_size, reset_histograms, resolve_ladder,
                     seed_cache_from_observed)
from .measure import (cached_step_ms, jit_cost, measure_or_model,
                      model_score, note_step_timing, step_shape_key)

__all__ = [
    "TuningCache", "CACHE_FILENAME", "device_kind", "get_cache",
    "reset_cache", "scoped", "tuned_value",
    "ShapeHistogram", "observe", "histogram", "histograms",
    "merge_observed", "reset_histograms", "derive_ladder",
    "expected_padding_waste",
    "percentile_size", "resolve_ladder", "seed_cache_from_observed",
    "measure_or_model", "jit_cost", "model_score", "step_shape_key",
    "note_step_timing", "cached_step_ms",
]
