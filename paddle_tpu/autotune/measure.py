"""Measure-or-model candidate selection + the executor's step-timing
log (ISSUE 8).

TVM (PAPERS.md) picks schedules by measuring candidates when it can and
consulting a cost model when it can't; this is that loop at framework
granularity:

  - ``measure_or_model(tunable_id, candidates, runner=...)`` — when a
    real executable exists, each candidate is timed (median of ``k``
    runs after one warmup, so jit compiles never pollute the sample)
    and the fastest wins; the decision lands in the tuning cache under
    (device_kind, tunable_id, shape_key), so a REPEAT session returns
    it without running anything.
  - ``measure_or_model(..., cost_fn=...)`` — the zero-run fallback:
    ``cost_fn(candidate)`` returns an XLA ``cost_analysis`` dict
    (``jit_cost`` below lowers a jax callable and extracts it via
    jax_compat, so the 0.4.37 list-vs-dict skew stays in one place) and
    the candidate with the lowest ``flops + bytes_accessed`` proxy
    wins. The proxy only ORDERS structurally different candidates —
    prefer measurement whenever a runner is available.
  - ``note_step_timing(tunable_id, program, feeds, ms)`` — the
    executor hook: every steady-state (non-compile) step's wall time is
    logged under a stable program/shape fingerprint, so the cache
    accumulates per-shape step costs across sessions and
    ``cached_step_ms`` can answer "have we measured this before?"
    without running it again.
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics as _metrics, tracing as _tracing
from .cache import TuningCache, get_cache, _median

__all__ = ["measure_or_model", "jit_cost", "model_score",
           "step_shape_key", "note_step_timing", "cached_step_ms"]

# one inc per TIMED candidate run — a bench re-run with a warm cache
# proves the skip by this counter's delta staying 0
_m_measurements = _metrics.counter("autotune.measurements")
_m_modeled = _metrics.counter("autotune.modeled")


def _canon(v: Any) -> Any:
    """JSON-round-trip normalization (tuples -> lists, int keys ->
    str): cached decisions are compared in the form they persist in."""
    try:
        return json.loads(json.dumps(v))
    except (TypeError, ValueError):
        return v


def model_score(cost: Dict[str, Any]) -> float:
    """Unitless cost-model proxy over an XLA cost_analysis dict:
    ``flops + bytes_accessed``. Good enough to order candidates that
    differ structurally (a fused vs unfused graph, a kernel vs a
    gather-then-dense reference); NOT a latency estimate — measured
    runs always override it in the cache (source 'measured' vs
    'model')."""
    flops = float(cost.get("flops") or 0.0)
    bytes_acc = float(cost.get("bytes accessed")
                      or cost.get("bytes_accessed") or 0.0)
    return flops + bytes_acc


def jit_cost(fn: Callable, *args, **kw) -> Dict[str, Any]:
    """Zero-run cost extraction: trace/lower ``fn`` at the given
    arguments (pure tracing — no XLA compile) and return its
    cost_analysis dict via jax_compat (which owns the 0.4.37 skew)."""
    import jax

    from .. import jax_compat as _jc

    return _jc.cost_analysis_dict(jax.jit(fn).lower(*args, **kw))


def measure_or_model(tunable_id: str, candidates: Sequence[Any], *,
                     runner: Optional[Callable[[Any], Any]] = None,
                     cost_fn: Optional[Callable[[Any], Dict[str, Any]]]
                     = None,
                     k: int = 5, shape_key: str = "",
                     cache: Optional[TuningCache] = None,
                     device: Optional[str] = None
                     ) -> Tuple[Any, Dict[str, Any]]:
    """Pick the best candidate and persist the decision.

    Returns ``(best, evidence)`` where evidence carries the per-
    candidate scores and the source ('cache' when a previous session
    already decided — nothing is run in that case)."""
    cands = list(candidates)
    if not cands:
        raise ValueError("measure_or_model needs at least one candidate")
    c = cache or get_cache()
    prior = c.lookup(tunable_id, shape_key=shape_key, device=device)
    if prior is not None:
        # match through JSON canonicalization: a persisted tuple comes
        # back as a list, and the repeat-session skip must still fire —
        # the caller gets ITS candidate object back, not the JSON form
        pc = _canon(prior)
        for cand in cands:
            if _canon(cand) == pc:
                return cand, {"source": "cache", "value": cand}
    scores: List[float] = []
    if runner is not None:
        with _tracing.span("autotune.measure", tunable=str(tunable_id),
                           candidates=len(cands)):
            for cand in cands:
                runner(cand)  # warmup: the jit compile never counts
                times = []
                for _ in range(max(1, int(k))):
                    t0 = time.perf_counter()
                    runner(cand)
                    times.append((time.perf_counter() - t0) * 1e3)
                    _m_measurements.inc()
                scores.append(round(_median(times), 4))
        source = "measured"
    elif cost_fn is not None:
        for cand in cands:
            scores.append(float(model_score(cost_fn(cand))))
            _m_modeled.inc()
        source = "model"
    else:
        raise ValueError("need a runner (measure) or a cost_fn (model)")
    # ties break to the FIRST candidate — callers order by preference
    best_i = min(range(len(cands)), key=lambda i: (scores[i], i))
    best = cands[best_i]
    evidence = {"source": source,
                "scores": {str(cand): s for cand, s in zip(cands, scores)},
                "value": best}
    c.put(tunable_id, best, shape_key=shape_key, source=source,
          device=device,
          extra={"scores": evidence["scores"]})
    return best, evidence


# -- the executor's per-shape step log -----------------------------------

def _program_fingerprint(program) -> str:
    """Hash of the op-type sequence AND the declared var shapes —
    op types alone would pool two same-stack models of different
    widths (an fc size=64 vs size=4096 has identical op types and feed
    shapes; only the weight vars differ) into one timing record.
    Memoized on the Program per version: the per-step path must not
    rehash a multi-thousand-op program."""
    cached = getattr(program, "_autotune_fingerprint", None)
    if cached is not None and cached[0] == program._version:
        return cached[1]
    block = program.global_block()
    ops = ",".join(op.desc.type for op in block.ops)
    shapes = ",".join(f"{n}:{tuple(v.shape) if v.shape else ()}"
                      for n, v in sorted(block.vars.items()))
    h = hashlib.md5(f"{ops}|{shapes}".encode()).hexdigest()[:8]
    program._autotune_fingerprint = (program._version, h)
    return h


def _dtype_name(v) -> str:
    # no np.asarray: materializing a jax feed just to name its dtype
    # would be a device->host transfer on the per-step path
    dt = getattr(v, "dtype", None)
    return str(dt) if dt is not None else str(np.asarray(v).dtype)


def step_shape_key(program, feeds: Dict[str, Any]) -> str:
    """Stable fingerprint of (program structure, feed shapes/dtypes) —
    deliberately NOT ``program._version`` (a per-process counter that
    would never match across sessions): the op-type sequence hash plus
    the sorted feed signature."""
    sig = ";".join(
        f"{name}:{_dtype_name(v)}{tuple(np.shape(v))}"
        for name, v in sorted(feeds.items()))
    return f"{_program_fingerprint(program)}|{sig}"


def note_step_timing(tunable_id: str, program, feeds: Dict[str, Any],
                     ms: float):
    """Log one steady-state step time under the program/shape key (the
    ``FLAGS['autotune']`` executor hook — compile runs are excluded by
    the caller)."""
    get_cache().note_timing(tunable_id, step_shape_key(program, feeds),
                            float(ms))


def cached_step_ms(tunable_id: str, program,
                   feeds: Dict[str, Any]) -> Optional[float]:
    """Median step ms a previous session recorded for this exact
    program/shape, or None — the repeat-session measurement skip."""
    rec = get_cache().timing(tunable_id, step_shape_key(program, feeds))
    return float(rec["median_ms"]) if rec else None
