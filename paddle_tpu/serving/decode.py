"""Autoregressive decode serving: continuous batching over a paged KV
cache (ISSUE 6; PAPERS.md: Ragged Paged Attention).

The one-shot engine (engine.py) answers each request with one model
run. Autoregressive decode is different in kind: a request is a
SEQUENCE of dependent steps (one per generated token), each step needs
the sequence's whole KV history on-device, and sequences finish at
ragged, data-dependent times. Two naive designs fail on TPU:

  - drain-per-batch (admit a batch, run every member to completion,
    then admit the next): short sequences finish early and their slots
    idle until the longest member drains — realized tokens/s decays
    with length variance (decode_bench measures exactly this);
  - per-sequence shapes: recompiling per ragged length mints O(shapes)
    jit entries under the traffic that can least afford compiles.

This engine does CONTINUOUS batching over FIXED compiled shapes:

  - the decode batch has a fixed slot layout — slot count padded to a
    small ladder (``FLAGS['decode_slots']``), per-slot page-table width
    padded to a derived ladder — and ``warm()`` pre-compiles every
    (slots, width) pair at load time, exactly like the one-shot
    engine's bucket warm. After warmup a churn of admits/completions
    at ragged lengths performs ZERO new compiles (tier-1 pins the
    ``serving.decode.compiles`` counter);
  - every step consumes up to ``prefill_chunk`` PROMPT tokens plus one
    generated token per decoding slot (ISSUE 10, chunked prefill):
    sequences still in their prompt are granted chunks of it — causal
    within the chunk, all slots sharing a per-step token BUDGET of
    ``prefill_chunk`` prompt tokens — while sequences past their
    prompt consume their previously sampled token, all in the SAME
    compiled mixed batch (Sarathi-style). A P-token prompt completes
    prefill in ``ceil(P / prefill_chunk)`` steps instead of P, so
    time-to-first-token stops being linear in prompt length, and
    in-flight decodes never stall behind a long prompt. New sequences
    are admitted into free slots BETWEEN steps, mid-flight of everyone
    else — admission never waits for a batch boundary;
  - K/V live in the preallocated paged pool (kv_cache.py): HBM is
    bounded at construction, pages are reserved at admission (refusal
    is an immediate structured ``ServerOverloaded``) and recycled at
    completion, and the paged-attention kernel reads through the page
    tables so ragged histories share one compiled shape.

The model behind the step is pluggable via the ``DecoderSpec`` /
``build_decoder_params`` / ``decoder_step`` contract below; the
built-in spec'd decoder (embedding + N pre-norm transformer layers
with paged attention + tied-embedding logits, deterministic params
from a seed) is the test/bench/selftest vehicle — real checkpoints
implement the same step signature.

Lifecycle mirrors the one-shot engine so the SAME ModelRegistry
hot-swaps decoders: ``stop(drain=True)`` finishes every admitted
sequence then drops params/pools/compiled steps (executables release
on retirement); a failed ``warm()`` stops the scheduler before
re-raising so the registry's rollback leaks nothing.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..autotune.ladder import observe as _observe_shape
from ..distributed import faults as _faults
from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger
from .engine import bucket_for as _bucket_for, resolve_bucket_spec
from .errors import (DeadlineExceeded, EngineRetired, RequestTooLarge,
                     ServerOverloaded, ServingError)
from .kv_cache import GARBAGE_PAGE, HostSpillStore, PagedKvCache

__all__ = ["DecoderSpec", "DecodeEngine", "build_decoder_params",
           "decoder_step", "decoder_step_chunked", "width_ladder",
           "sample_token"]

_log = get_logger("serving")

_m_requests = _metrics.counter("serving.decode.requests")
_m_admitted = _metrics.counter("serving.decode.admitted")
_m_completions = _metrics.counter("serving.decode.completions")
_m_steps = _metrics.counter("serving.decode.steps")
_m_tokens = _metrics.counter("serving.decode.tokens")
_m_overloads = _metrics.counter("serving.decode.overloads")
_m_deadline_miss = _metrics.counter("serving.decode.deadline_misses")
_m_cancels = _metrics.counter("serving.decode.cancels")
# one inc per DISTINCT (slots, width) shape the step compiles — after
# warm() this must never move again (the tier-1 churn guard pins it)
_m_compiles = _metrics.counter("serving.decode.compiles")
_m_step_ms = _metrics.histogram("serving.decode.step_ms")
_m_queue_wait = _metrics.histogram("serving.decode.queue_wait_ms")
_m_total = _metrics.histogram("serving.decode.total_ms")
# live slots / slot bucket per step: the continuous-batching win is
# this histogram staying fat while drain-per-batch's decays
_m_occupancy = _metrics.histogram("serving.decode.occupancy")
# chunked prefill (ISSUE 10): prompt tokens consumed via prefill
# grants, per-step grant totals (prices the token-budget policy next
# to the occupancy/fragmentation gauges), and how many scheduler steps
# each request waited for its FIRST generated token — the
# load-independent evidence chunking exists for (ceil(P/chunk) + queue
# wait, vs P + queue wait unchunked)
_m_prefill_tokens = _metrics.counter("serving.decode.prefill_tokens")
_m_prefill_per_step = _metrics.histogram(
    "serving.decode.prefill_tokens_per_step")
_m_first_token_steps = _metrics.histogram(
    "serving.decode.steps_to_first_token")
# preempt+restore (ISSUE 13, demand-mode reservation): preemptions
# spill a victim's pages to host and requeue it at the front; restores
# scatter them back bitwise; demotions release a QUEUED reservation
# (no computed work lost) so a live grower can proceed
_m_preemptions = _metrics.counter("serving.kv.preemptions")
_m_restores = _metrics.counter("serving.kv.restores")
_m_demotions = _metrics.counter("serving.kv.demotions")


# --- the pluggable decoder model ----------------------------------------

class DecoderSpec:
    """Architecture + identity of a decoder the engine can serve.
    ``d_model == n_heads * head_dim`` (enforced); ``n_heads`` must be a
    multiple of ``n_kv_heads`` (GQA). Params are DETERMINISTIC in
    ``seed`` so two replicas loading the same spec serve bitwise the
    same model — and tests can reference-check outputs."""

    __slots__ = ("vocab", "d_model", "n_layers", "n_heads", "n_kv_heads",
                 "head_dim", "seed", "eos_id")

    def __init__(self, vocab: int = 64, d_model: int = 32,
                 n_layers: int = 2, n_heads: int = 4,
                 n_kv_heads: Optional[int] = None, seed: int = 0,
                 eos_id: Optional[int] = None):
        self.vocab = int(vocab)
        self.d_model = int(d_model)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.n_kv_heads = int(n_kv_heads if n_kv_heads is not None
                              else n_heads)
        if self.d_model % 2:
            raise ValueError(f"d_model {d_model} must be even "
                             f"(sinusoidal encoding pairs sin/cos halves)")
        if self.d_model % self.n_heads:
            raise ValueError(f"d_model {d_model} not divisible by "
                             f"n_heads {n_heads}")
        if self.n_heads % self.n_kv_heads:
            raise ValueError(f"n_heads {n_heads} not a multiple of "
                             f"n_kv_heads {self.n_kv_heads}")
        self.head_dim = self.d_model // self.n_heads
        self.seed = int(seed)
        self.eos_id = None if eos_id is None else int(eos_id)

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in
                ("vocab", "d_model", "n_layers", "n_heads", "n_kv_heads",
                 "seed", "eos_id")}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DecoderSpec":
        allowed = ("vocab", "d_model", "n_layers", "n_heads",
                   "n_kv_heads", "seed", "eos_id")
        # reject, don't drop: a misspelled field silently deploying a
        # default-architecture decoder is a wrong-model hot-swap
        # (head_dim is derived — accepted only if consistent)
        unknown = sorted(set(d) - set(allowed) - {"head_dim"})
        if unknown:
            raise ValueError(
                f"unknown DecoderSpec field(s) {unknown}; "
                f"valid: {sorted(allowed)}")
        spec = cls(**{k: v for k, v in d.items() if k in allowed})
        if "head_dim" in d and int(d["head_dim"]) != spec.head_dim:
            raise ValueError(
                f"head_dim {d['head_dim']} contradicts d_model "
                f"{spec.d_model} / n_heads {spec.n_heads} = "
                f"{spec.head_dim} — head_dim is derived, not free")
        return spec


def build_decoder_params(spec: DecoderSpec) -> Dict[str, Any]:
    """Deterministic parameter tree (seeded numpy draws, scaled-normal
    init) — the test/bench stand-in for loading a checkpoint."""
    import jax.numpy as jnp

    rng = np.random.RandomState(spec.seed)
    dm, dh = spec.d_model, spec.head_dim

    def mat(fan_in, *shape):
        return jnp.asarray(
            (rng.randn(*shape) / math.sqrt(fan_in)).astype(np.float32))

    params: Dict[str, Any] = {
        "tok_emb": mat(dm, spec.vocab, dm),
        "lnf": (jnp.ones((dm,), jnp.float32), jnp.zeros((dm,), jnp.float32)),
    }
    for l in range(spec.n_layers):
        params[f"layer{l}"] = {
            "ln1": (jnp.ones((dm,), jnp.float32),
                    jnp.zeros((dm,), jnp.float32)),
            "wq": mat(dm, dm, spec.n_heads * dh),
            "wk": mat(dm, dm, spec.n_kv_heads * dh),
            "wv": mat(dm, dm, spec.n_kv_heads * dh),
            "wo": mat(dm, spec.n_heads * dh, dm),
            "ln2": (jnp.ones((dm,), jnp.float32),
                    jnp.zeros((dm,), jnp.float32)),
            "w1": mat(dm, dm, 4 * dm),
            "w2": mat(4 * dm, 4 * dm, dm),
        }
    return params


def _ln(x, gb):
    import jax.numpy as jnp

    g, b = gb
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-6) * g + b


def _pos_encoding(positions, d_model):
    """Sinusoidal [B, d_model] — unbounded positions, no learned table
    to cap sequence length."""
    import jax.numpy as jnp

    half = d_model // 2
    freq = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
    ang = positions[:, None].astype(jnp.float32) * freq[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


def decoder_step_chunked(params, spec: DecoderSpec, tokens, positions,
                         q_lens, k_pool, v_pool, page_tables, kv_lens):
    """ONE mixed decode/prefill step for a fixed-slot batch
    (ISSUE 10). Each slot carries up to C tokens of ITS sequence — a
    prefill chunk, a single decode token at C lane 0, or nothing —
    attending causally within the chunk. Functional: writes every
    valid lane's K/V into the paged pools (dead lanes and dead slots
    write the garbage page), attends through the page tables, returns
    ``(k_pool, v_pool, logits [B, vocab])``.

    tokens/positions: [B, C] int32, lane ``j`` of slot ``i`` valid iff
    ``j < q_lens[i]`` (invalid lanes: 0/0 — masked to the garbage
    page, never trusted). kv_lens: [B] int32 — valid keys INCLUDING
    this step's q_len tokens. Chunking is pure packing: the math per
    token is identical to feeding the same tokens one step at a time
    (the chunked-vs-unchunked greedy-equality test pins it).

    Logits come back ONLY for each slot's newest lane (``q_len - 1``)
    — the one position the scheduler ever samples from (a chunk that
    doesn't finish its prompt uses no logits at all). Unembedding is
    the widest matmul of the step: unembedding all C lanes would waste
    ~(C-1)/C of it plus a C-times-larger device->host transfer on
    every prefill step.
    """
    import jax
    import jax.numpy as jnp

    from ..fluid.ops.pallas_kernels.paged_attention import paged_attention

    b, c = tokens.shape
    ps = k_pool.shape[2]
    dm, dh = spec.d_model, spec.head_dim
    lane = jnp.arange(c)[None, :]                      # [1, C]
    valid = lane < q_lens[:, None]                     # [B, C]
    x = params["tok_emb"][tokens] * math.sqrt(dm) + \
        _pos_encoding(positions.reshape(-1), dm).reshape(b, c, dm)
    page_idx = positions // ps
    # each lane's physical page: its slot's table row at the token's
    # page index. Invalid lanes (j >= q_len, padded dead slots) are
    # FORCED to the garbage page — a live slot's row 0 must never be
    # clobbered by a dead lane's position-0 write
    page = jnp.where(valid,
                     jnp.take_along_axis(page_tables, page_idx, axis=1),
                     GARBAGE_PAGE)                     # [B, C]
    off = jnp.where(valid, positions % ps, 0)
    for l in range(spec.n_layers):
        lp = params[f"layer{l}"]
        h = _ln(x, lp["ln1"])
        q = (h @ lp["wq"]).reshape(b, c, spec.n_heads, dh)
        k = (h @ lp["wk"]).reshape(b, c, spec.n_kv_heads, dh)
        v = (h @ lp["wv"]).reshape(b, c, spec.n_kv_heads, dh)
        # write the whole chunk's K/V, THEN attend: within the chunk,
        # query j sees keys i <= j of the same chunk — write-before-
        # attend makes the chunk exactly equal to sequential steps
        k_pool = k_pool.at[l, page, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[l, page, off].set(v.astype(v_pool.dtype))
        attn = paged_attention(q, k_pool[l], v_pool[l], page_tables,
                               kv_lens, q_lens=q_lens)
        x = x + attn.reshape(b, c, spec.n_heads * dh) @ lp["wo"]
        h2 = _ln(x, lp["ln2"])
        x = x + jax.nn.gelu(h2 @ lp["w1"]) @ lp["w2"]
    # unembed only each slot's newest lane (dead slots gather lane 0 —
    # garbage the scheduler never samples)
    last = jnp.maximum(q_lens - 1, 0)[:, None, None]       # [B, 1, 1]
    x_last = jnp.take_along_axis(
        x, jnp.broadcast_to(last, (b, 1, dm)), axis=1)[:, 0]
    logits = _ln(x_last, params["lnf"]) @ params["tok_emb"].T
    return k_pool, v_pool, logits


def decoder_step(params, spec: DecoderSpec, tokens, positions,
                 k_pool, v_pool, page_tables, kv_lens):
    """The PR 6 single-token step — now the C=1 case of
    ``decoder_step_chunked`` (one implementation, so the two forms
    cannot drift). tokens/positions: [B] int32 (dead slots: 0/0 with
    an all-garbage table row); kv_lens: [B] int32 — valid keys
    INCLUDING this step's token (0 = dead slot -> exact-zero attention
    output). Returns ``(k_pool, v_pool, logits [B, vocab])``."""
    import jax.numpy as jnp

    q_lens = (kv_lens > 0).astype(jnp.int32)
    return decoder_step_chunked(
        params, spec, tokens[:, None], positions[:, None], q_lens,
        k_pool, v_pool, page_tables, kv_lens)


# --- sampling -----------------------------------------------------------

def sample_token(logits_row, temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, position: int = 0) -> int:
    """Sampling policy for ONE generated token (the ROADMAP
    sampling-beyond-greedy residual): greedy argmax at temperature 0
    (the default — bitwise the PR 6 behavior), else temperature-scaled
    softmax over the ``top_k`` highest logits (0 = full vocab), drawn
    from an rng derived ONLY from ``(seed, position)``.

    Deterministic given the request's seed, and — because position is
    the token's absolute index in ITS sequence — independent of batch
    composition, slot assignment, and admission order: continuous
    batching cannot perturb a request's sampled output (tier-1 pins a
    request decoding identically through two differently-loaded
    engines)."""
    row = np.asarray(logits_row, np.float64)
    if temperature <= 0.0:
        return int(np.argmax(row))
    row = row / float(temperature)
    k = int(top_k)
    if 0 < k < row.size:
        kth = np.partition(row, -k)[-k]
        row = np.where(row < kth, -np.inf, row)
    row = row - row.max()
    p = np.exp(row)
    p /= p.sum()
    rng = np.random.Generator(np.random.PCG64(np.random.SeedSequence(
        [int(seed) & 0xFFFFFFFF, int(position)])))
    return int(rng.choice(row.size, p=p))


# --- ladders ------------------------------------------------------------

def width_ladder(max_pages: int) -> List[int]:
    """Page-table width buckets: powers of two up to (and always
    including) the worst case — the second padded dimension of the
    compiled decode shape."""
    if max_pages < 1:
        raise ValueError(f"max_pages must be >= 1, got {max_pages}")
    out, w = [], 1
    while w < max_pages:
        out.append(w)
        w *= 2
    out.append(max_pages)
    return sorted(set(out))


# --- requests / slots ---------------------------------------------------

class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "deadline", "ev", "result", "error",
                 "t_enq", "seq_id", "trace_ctx", "temperature", "top_k",
                 "seed", "produced", "cached_tokens", "cow", "resume_pos",
                 "published", "carry_steps", "carry_fts", "needs_alloc")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 deadline: Optional[float], seq_id: int,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.deadline = deadline
        self.ev = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[BaseException] = None
        self.t_enq = time.monotonic()
        self.seq_id = seq_id
        self.trace_ctx = _tracing.wire_context()
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        # generated tokens, appended by the answer phase UNDER the
        # engine's _cond. Living on the REQUEST (not the slot) so
        # streaming readers (stream_tokens, ISSUE 12) can see tokens
        # the moment they exist, long before the sequence finishes
        self.produced: List[int] = []
        # prefix caching + preemption state (ISSUE 13) — on the REQUEST
        # because preemption round-trips a sequence through the queue:
        # cached_tokens = prompt tokens answered from the prefix index
        # (prefill starts past them); cow = the pending private-copy of
        # a shared partial page (executed by the scheduler before the
        # first step, then None); resume_pos/carry_* = the exact point
        # a preempted sequence continues from; needs_alloc = the
        # reservation was surrendered (preempt/demote) and admission
        # must re-reserve before taking a slot
        self.cached_tokens = 0
        self.cow: Optional[Dict[str, int]] = None
        self.resume_pos: Optional[int] = None
        self.published = False
        self.carry_steps = 0
        self.carry_fts: Optional[int] = None
        self.needs_alloc = False

    def fail(self, err: BaseException):
        self.error = err
        self.ev.set()


class _Slot:
    __slots__ = ("req", "pos", "pages_held", "steps", "first_token_steps",
                 "pending_restore")

    def __init__(self, req: _DecodeRequest, pages_held: int):
        self.req = req
        self.pos = 0                # tokens already written to the cache
        self.pages_held = pages_held
        self.steps = 0              # scheduler steps this slot has ridden
        self.first_token_steps: Optional[int] = None
        # a preempted sequence's spilled pages must scatter back into
        # its fresh reservation BEFORE its next step (restore-before-
        # step): set at re-admission, executed by _prepare
        self.pending_restore = False

    def token_at(self, idx: int) -> int:
        """The sequence's token at absolute position ``idx``: a prompt
        token, or a previously generated one."""
        p = self.req.prompt
        return (int(p[idx]) if idx < len(p)
                else self.req.produced[idx - len(p)])


# --- the engine ---------------------------------------------------------

class DecodeEngine:
    """Continuous-batching autoregressive decode over one loaded
    decoder. Registry/server-compatible: ``name``/``version``/``kind``/
    ``stats()``/``stop(drain=)`` mirror InferenceEngine, so the same
    ModelRegistry hot-swaps decoders with the same drain guarantee."""

    kind = "decoder"

    def __init__(self, spec: DecoderSpec, *, name: str = "decoder",
                 version: int = 1,
                 slots: Optional[Sequence[int]] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 continuous: bool = True,
                 params: Optional[Dict[str, Any]] = None,
                 prefix_cache: Optional[bool] = None,
                 reservation: Optional[str] = None,
                 spill_dir: Optional[str] = None,
                 warm: bool = True):
        from ..fluid.flags import FLAGS, effective_flag

        self.name = str(name)
        self.version = int(version)
        self.spec = spec
        # shares _step_mu with the compiled step + shape set: the lock
        # serializes every read-step-rebind against retirement's drop
        self._params = (build_decoder_params(spec)
                        if params is None else params)  # guarded-by: _step_mu
        # slots="auto" resolves through the tuner exactly like the
        # one-shot engine's buckets="auto": a derived ladder from the
        # observed slot-demand histogram (or the cached one), else the
        # static FLAGS default — fixed before warm() either way
        self._slot_ladder = resolve_bucket_spec(
            FLAGS["decode_slots"] if slots is None else slots,
            tunable_id="decode_slots", fallback="1,2,4")
        self._max_slots = self._slot_ladder[-1]
        ps = int(FLAGS["kv_page_size"] if page_size is None else page_size)
        npages = int(FLAGS["kv_num_pages"] if num_pages is None
                     else num_pages)
        self.max_seq_len = int(FLAGS["decode_max_seq_len"]
                               if max_seq_len is None else max_seq_len)
        self._max_queue = int(FLAGS["serving_max_queue"]
                              if max_queue is None
                              else max_queue)  # guarded-by: _cond
        # drain-per-batch mode (continuous=False) exists ONLY as the
        # honest A/B baseline for decode_bench — same engine, same
        # compiled shapes, admission gated on an empty batch
        self._continuous = bool(continuous)
        # prefix caching + reservation policy (ISSUE 13). demand mode
        # reserves the prompt's pages plus kv_decode_headroom pages at
        # admission and grows mid-decode (preempting when the pool runs
        # dry); worst_case is the PR 6 reserve-everything policy, kept
        # as the bench's admitted-concurrency baseline
        self._prefix_on = bool(FLAGS["prefix_cache"]
                               if prefix_cache is None else prefix_cache)
        reservation = str(FLAGS["kv_reservation"]
                          if reservation is None else reservation)
        if reservation not in ("demand", "worst_case"):
            raise ValueError(
                f"reservation must be 'demand' or 'worst_case', "
                f"got {reservation!r}")
        self._reservation = reservation
        self._headroom_pages = max(0, int(FLAGS["kv_decode_headroom"]))
        self.cache = PagedKvCache(
            spec.n_layers, spec.n_kv_heads, spec.head_dim,
            page_size=ps, num_pages=npages,
            label=f"{self.name}.v{self.version}",
            prefix_cache=self._prefix_on)
        # host refuge for preempted sequences' pages (kv_spill_dir
        # moves it to disk); cleared at retirement — leaks nothing
        self._spill = HostSpillStore(
            spill_dir=spill_dir, label=f"{self.name}.v{self.version}")
        w_max = self.cache.allocator.pages_for_tokens(self.max_seq_len)
        self._width_ladder = width_ladder(w_max)
        # chunked prefill (ISSUE 10): the per-step prompt-token budget
        # AND the compiled chunk width. A PR 8 tunable: the FLAGS
        # constant is the cold default, the autotune cache overrides
        # per device kind (decode_bench seeds it via measure-or-model
        # and the observed prompt-length histogram). Clamped to the
        # longest admissible prompt (max_seq_len - 1: max_new >= 1) —
        # a wider chunk than any prompt only burns warm compiles.
        # Resolved ONCE, before warm(), like every other ladder knob.
        chunk = int(effective_flag("prefill_chunk")
                    if prefill_chunk is None else prefill_chunk)
        self._prefill_chunk = max(1, min(chunk, max(1,
                                                    self.max_seq_len - 1)))
        # the third padded dimension of the compiled step: pure-decode
        # steps ride the C=1 shapes (exactly the PR 6 step — chunking
        # costs nothing when no prompt is in flight), steps carrying a
        # prefill grant ride the C=chunk shapes
        self._chunk_ladder = sorted({1, self._prefill_chunk})
        self._cond = threading.Condition()
        self._queue: List[_DecodeRequest] = []  # guarded-by: _cond
        self._slots: List[_Slot] = []  # guarded-by: _cond
        self._stopping = False  # guarded-by: _cond
        self._released = False  # guarded-by: _cond
        self._seq_counter = 0  # guarded-by: _cond
        self._n_requests = 0  # guarded-by: _cond
        self._n_steps = 0  # guarded-by: _cond
        self._compiled_shapes: set = set()  # guarded-by: _step_mu
        self._g_depth = _metrics.gauge(
            f"serving.decode.queue_depth.{self.name}.v{self.version}")
        # per-instance for the same reason as queue_depth: a draining
        # old version must not clobber the live engine's value
        self._g_live = _metrics.gauge(
            f"serving.decode.live_slots.{self.name}.v{self.version}")

        import jax

        spec_ref = spec  # closed over; jit retraces only on shape change

        def _step(params, tokens, positions, q_lens, k_pool, v_pool,
                  tables, lens):
            return decoder_step_chunked(params, spec_ref, tokens,
                                        positions, q_lens, k_pool,
                                        v_pool, tables, lens)

        # donate the pools on TPU so XLA updates the KV pages in place
        # (HBM footprint stays the preallocated pool); CPU ignores
        # donation, so skip it there to avoid per-call warnings
        donate = (bool(FLAGS["donate_state"])
                  and jax.default_backend() == "tpu")
        self._donate = donate
        self._step_fn = jax.jit(
            _step,
            donate_argnums=(4, 5) if donate else ())  # guarded-by: _step_mu
        # serializes warm() (caller thread) against live steps (the
        # scheduler thread): read-pools -> step -> rebind must be
        # atomic or concurrent rebinds silently drop KV writes
        self._step_mu = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"decode-{self.name}-v{self.version}")
        self._thread.start()
        if warm:
            try:
                self.warm()
            except BaseException:
                # failed warm is the registry's rollback path: the
                # scheduler thread (and the params/pools it pins) must
                # not outlive the failed deploy
                self.stop(drain=False)
                raise

    # -- public surface ---------------------------------------------------
    @property
    def slot_ladder(self) -> List[int]:
        return list(self._slot_ladder)

    @property
    def table_width_ladder(self) -> List[int]:
        return list(self._width_ladder)

    @property
    def prefill_chunk(self) -> int:
        return self._prefill_chunk

    @property
    def chunk_ladder(self) -> List[int]:
        return list(self._chunk_ladder)

    def warm(self):
        """Pre-compile EVERY (slot-count, table-width, chunk) triple on
        an all-dead synthetic batch (writes land on the garbage page).
        After this, sequence churn at ragged lengths — prefill chunks
        included — compiles nothing: all three padded dimensions only
        ever take ladder values."""
        with _tracing.span("serving.decode.warmup", model=self.name,
                           version=self.version):
            for s in self._slot_ladder:
                for w in self._width_ladder:
                    for c in self._chunk_ladder:
                        self._run_step_arrays(
                            np.zeros((s, c), np.int32),
                            np.zeros((s, c), np.int32),
                            np.zeros(s, np.int32),
                            np.full((s, w), GARBAGE_PAGE, np.int32),
                            np.zeros(s, np.int32))

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               temperature: float = 0.0, top_k: int = 0,
               seed: int = 0) -> _DecodeRequest:
        """Validate + reserve KV pages + enqueue. All refusals are
        synchronous and typed: ``ServerOverloaded`` (queue full OR page
        pool exhausted), ``RequestTooLarge`` (can't ever fit),
        ``EngineRetired``, ``ValueError`` (bad tokens / bad sampling
        params). ``temperature``/``top_k``/``seed`` select the sampling
        policy per request (``sample_token``; 0.0 = greedy)."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if int(prompt.min()) < 0 or int(prompt.max()) >= self.spec.vocab:
            raise ValueError(
                f"prompt token ids must be in [0, {self.spec.vocab})")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = int(prompt.size) + max_new
        if total > self.max_seq_len:
            raise RequestTooLarge(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) = "
                f"{total} exceeds max_seq_len {self.max_seq_len}")
        if self._reservation == "demand" and \
                self.cache.allocator.pages_for_tokens(total) > \
                self.cache.num_pages - 1:
            # demand mode admits beyond the worst case, so the ONLY
            # hard bound is "could this sequence fit even alone, with
            # everyone else preempted" — refuse up front if not (the
            # growth path's progress guarantee depends on it)
            raise RequestTooLarge(
                f"worst case {total} tokens = "
                f"{self.cache.allocator.pages_for_tokens(total)} pages "
                f"exceeds the whole pool "
                f"({self.cache.num_pages - 1} usable pages)")
        temperature = float(temperature)
        top_k = int(top_k)
        if temperature < 0.0 or not math.isfinite(temperature):
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        with self._cond:
            if self._stopping:
                raise EngineRetired(
                    f"decoder '{self.name}' v{self.version} is retiring")
            if len(self._queue) >= self._max_queue:
                _m_overloads.inc()
                raise ServerOverloaded(
                    f"decoder '{self.name}' queue is full "
                    f"({self._max_queue} deep)")
            self._seq_counter += 1
            seq_id = self._seq_counter
            try:
                # reserve NOW: worst_case mode takes the whole
                # prompt+max_new bound (an admitted sequence can then
                # never die of exhaustion); demand mode takes only the
                # prompt plus a small decode headroom — growth and
                # preemption own the tail (ISSUE 13). Either way the
                # pool is the admission bound (kv_cache.py) and the
                # refusal is typed and side-effect-free.
                res = self._reserve_locked(seq_id, prompt, total)
            except ServerOverloaded:
                _m_overloads.inc()
                raise
            req = _DecodeRequest(prompt, max_new, deadline, seq_id,
                                 temperature=temperature, top_k=top_k,
                                 seed=seed)
            req.cached_tokens = res["cached_tokens"]
            req.cow = res["cow"]
            self._queue.append(req)
            self._n_requests += 1
            self._g_depth.set(len(self._queue))
            # instantaneous concurrency demand — what slots="auto"
            # derives its ladder from (observed outside the lock)
            demand = len(self._queue) + len(self._slots)
            self._cond.notify()
        _observe_shape("decode_slots", demand)
        # the prompt-length histogram the prefill_chunk tuner derives
        # its crossover from (bench sessions seed it, ISSUE 10)
        _observe_shape("prefill_chunk", int(prompt.size))
        _m_requests.inc()
        return req

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None,
                 timeout: float = 300.0, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0) -> Dict[str, Any]:
        """Blocking convenience: submit + wait. Returns
        ``{"tokens": [...], "prompt_len": n, "version": v,
        "steps_to_first_token": k}``.
        ``temperature``/``top_k``/``seed`` thread through to the
        per-request sampler (0.0 = greedy, the default)."""
        req = self.submit(prompt, max_new_tokens, deadline_ms=deadline_ms,
                          temperature=temperature, top_k=top_k, seed=seed)
        if not req.ev.wait(timeout):
            # withdraw before raising: an abandoned sequence must not
            # keep its page reservation or burn further decode steps.
            # cancel() returning False means the request finished in
            # the wait-vs-cancel window — deliver that result, don't
            # discard paid-for tokens as a timeout
            if self.cancel(req):
                raise ServingError(
                    f"generate on '{self.name}' timed out after "
                    f"{timeout}s (decode scheduler wedged?)")
        if req.error is not None:
            raise req.error
        return req.result

    def cancel(self, req: _DecodeRequest,
               msg: str = "abandoned by caller") -> bool:
        """Withdraw a submitted request whose waiter gave up: frees its
        KV pages now and fails it, so the scheduler drops the slot at
        the next answer phase instead of decoding dead work to
        completion. A step already in flight still writes through the
        page table it captured BEFORE the free — safe today because a
        re-allocated page's every position is rewritten by its new
        owner in the same step that first attends to it
        (write-before-attend); the NEXT table build degrades the
        canceled row to the garbage page. Returns False if the
        request already finished."""
        with self._cond:
            if req.ev.is_set():
                return False
            if req in self._queue:
                self._queue.remove(req)
                self._g_depth.set(len(self._queue))
            _m_cancels.inc()
            self._fail_locked(req, ServingError(
                f"generate on '{self.name}' canceled: {msg}"))
            self._cond.notify_all()
            return True

    def stream_tokens(self, req: _DecodeRequest, offset: int,
                      timeout: float = 30.0) -> Dict[str, Any]:
        """Incremental token read for streaming generate (ISSUE 12):
        block until the sequence has tokens past ``offset`` (or it
        finished / failed / the wait lapses), then return everything
        past it. A PURE FUNCTION of (request state, offset) — it never
        advances hidden cursor state — which is what makes a
        retransmitted stream frame safe to answer from the dedup cache
        OR by re-execution: either way the client gets exactly the
        tokens at those offsets, with zero extra decode steps.

        Returns ``{"tokens", "offset", "next_offset", "done"}`` plus
        ``"result"`` once done; a failed request re-raises its typed
        error (DeadlineExceeded, EngineRetired, ...). A timeout with no
        new tokens returns an empty chunk with ``done=False`` — the
        caller polls again."""
        offset = int(offset)
        if offset < 0:
            raise ValueError(f"stream offset must be >= 0, got {offset}")
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            while len(req.produced) <= offset and not req.ev.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                # lint: allow-blocking — a bounded reader wait on the
                # engine's own condition; the answer phase notifies on
                # every step that produced a token
                self._cond.wait(remaining)
            toks = [int(t) for t in req.produced[offset:]]
            done = req.ev.is_set()
            err = req.error
            result = req.result
        if done and err is not None:
            raise err
        out: Dict[str, Any] = {"tokens": toks, "offset": offset,
                               "next_offset": offset + len(toks),
                               "done": done}
        if done:
            out["result"] = result
        return out

    def set_max_queue(self, n: int):
        with self._cond:
            self._max_queue = max(1, int(n))

    def stop(self, drain: bool = True, timeout: float = 300.0):
        """Refuse new work; ``drain`` completes every admitted AND
        queued sequence first (the hot-swap drain guarantee), else all
        are failed with EngineRetired. Then params/pools/compiled steps
        are dropped so retirement releases the executables and HBM."""
        with self._cond:
            self._stopping = True
            if not drain:
                for r in self._queue:
                    self._fail_locked(r, EngineRetired(
                        f"decoder '{self.name}' v{self.version} unloaded"))
                self._queue.clear()
                for s in self._slots:
                    # a slot _complete()d mid-step may still be in
                    # _slots (removal happens under _cond after the
                    # step) — never overwrite a delivered result
                    if not s.req.ev.is_set():
                        self._fail_locked(s.req, EngineRetired(
                            f"decoder '{self.name}' v{self.version} "
                            "unloaded"))
                    else:
                        self.cache.allocator.free(s.req.seq_id)
                self._slots = []
                self._g_depth.set(0)
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():  # pragma: no cover - wedged scheduler
            _log.error("decode scheduler for %s v%d did not exit in %.0fs",
                       self.name, self.version, timeout)
        # params/step/pools drop under _step_mu — THEIR guard (guards-lint
        # finding: they used to drop under _cond while _run_step_arrays
        # reads them under _step_mu; safe only by join-ordering, which a
        # static model can't see and a future warm()-after-stop wouldn't
        # honor)
        with self._step_mu:
            self._params = None
            self._step_fn = None
            self.cache.release()
        # any spills that survived the drain (preempted sequences the
        # retirement failed) die with the engine — files included
        self._spill.clear()
        with self._cond:
            self._released = True
            self._g_depth.set(0)
            # the scheduler may exit between steps without a final
            # answer phase — a retired engine must not report phantom
            # live slots
            self._g_live.set(0)

    def stats(self) -> Dict[str, Any]:
        # _compiled_shapes is _step_mu state: snapshot it under ITS lock
        # (guards-lint finding — sorted() here used to iterate the set
        # under _cond while the scheduler's _run_step_arrays add()ed to
        # it under _step_mu: a mid-iteration mutation raises
        # "Set changed size during iteration" on a stats scrape)
        with self._step_mu:
            shapes = sorted(self._compiled_shapes)
        with self._cond:
            return {
                "name": self.name,
                "version": self.version,
                "kind": self.kind,
                "spec": self.spec.to_dict(),
                "slots": list(self._slot_ladder),
                "table_widths": list(self._width_ladder),
                "prefill_chunk": self._prefill_chunk,
                "chunk_ladder": list(self._chunk_ladder),
                "page_size": self.cache.page_size,
                "max_seq_len": self.max_seq_len,
                "continuous": self._continuous,
                "reservation": self._reservation,
                "prefix_cache": self._prefix_on,
                "prefix": self.cache.allocator.prefix_stats(),
                "spilled_sequences": self._spill.count(),
                "kv": self.cache.allocator.stats(),
                "queue_depth": len(self._queue),
                "live": len(self._slots),
                "max_queue": self._max_queue,
                "requests": self._n_requests,
                "steps": self._n_steps,
                "compiled_shapes": shapes,
                "stopping": self._stopping,
            }

    # -- scheduler --------------------------------------------------------
    def _reserve_locked(self, seq_id: int, prompt, total: int
                        ) -> Dict[str, Any]:
        """One reservation under the engine's policy: demand = prompt
        pages + decode headroom (capped at the worst case), worst_case
        = everything. Prefix caching maps the cached chain read-only
        either way. Raises ``ServerOverloaded`` side-effect-free."""
        if self._reservation == "demand":
            reserve = min(total, len(prompt)
                          + self._headroom_pages * self.cache.page_size)
        else:
            reserve = total
        if self._prefix_on:
            return self.cache.allocator.alloc_prefix(seq_id, prompt,
                                                     reserve)
        self.cache.allocator.alloc(seq_id, reserve)
        return {"cached_tokens": 0, "cow": None}

    def _fail_locked(self, req: _DecodeRequest, err: BaseException):
        self.cache.allocator.free(req.seq_id)
        if req.cow is not None:
            # the COW source pin must not outlive the request (a pinned
            # entry is un-evictable)
            self.cache.allocator.release_cow(req.cow["key"])
            req.cow = None
        # a preempted request's host spill dies with it — cancel/
        # deadline/retirement mid-preemption leaks nothing
        self._spill.drop(req.seq_id)
        req.fail(err)

    def _drop_expired_locked(self, now: float):
        keep = []
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                _m_deadline_miss.inc()
                self._fail_locked(r, DeadlineExceeded(
                    f"request to decoder '{self.name}' missed its "
                    "deadline while queued"))
            else:
                keep.append(r)
        if len(keep) != len(self._queue):
            self._queue[:] = keep
            self._g_depth.set(len(keep))

    def _admit_locked(self):
        """Move queued requests into free slots. Continuous mode admits
        whenever a slot is free — INTO the in-flight batch; drain mode
        (the bench baseline) only refills an empty batch. A request
        whose reservation was surrendered (preempted victims sit at the
        queue FRONT, demoted reservations wherever they were) must
        re-reserve first; a refusal leaves it queued — completions and
        cache evictions free the pages it is waiting for."""
        if not self._continuous and self._slots:
            return
        while self._queue and len(self._slots) < self._max_slots:
            req = self._queue[0]
            if req.ev.is_set():
                # canceled / expired while queued — already failed
                self._queue.pop(0)
                continue
            if req.needs_alloc:
                total = len(req.prompt) + req.max_new
                try:
                    if req.resume_pos is not None:
                        # restore-before-step: cover what was spilled
                        # plus the decode headroom; prefix matching is
                        # deliberately NOT consulted — the spill is the
                        # bitwise truth (preempt-never-corrupts)
                        reserve = min(total, max(req.resume_pos, 1)
                                      + self._headroom_pages
                                      * self.cache.page_size)
                        self.cache.allocator.alloc(req.seq_id, reserve)
                    else:
                        res = self._reserve_locked(req.seq_id,
                                                   req.prompt, total)
                        req.cached_tokens = res["cached_tokens"]
                        req.cow = res["cow"]
                except ServerOverloaded:
                    break
                req.needs_alloc = False
            self._queue.pop(0)
            slot = _Slot(req,
                         self.cache.allocator.held_pages(req.seq_id))
            if req.resume_pos is not None:
                slot.pos = req.resume_pos
                slot.pending_restore = True
                req.resume_pos = None
            else:
                # cached prompt pages are already written (and mapped):
                # prefill starts at the first uncached token
                slot.pos = req.cached_tokens
            slot.steps = req.carry_steps
            slot.first_token_steps = req.carry_fts
            self._slots.append(slot)
            _m_admitted.inc()
            _m_queue_wait.observe((time.monotonic() - req.t_enq) * 1e3)
        self._g_depth.set(len(self._queue))
        self._g_live.set(len(self._slots))

    def _next_live(self) -> Optional[List[_Slot]]:
        # lint: allow-blocking — Condition.wait on the engine's own
        # condition is the scheduler's idle state by design
        with self._cond:
            while True:
                self._drop_expired_locked(time.monotonic())
                self._admit_locked()
                if self._slots:
                    return list(self._slots)
                if self._stopping and not self._queue:
                    return None
                # no live slots here implies the queue is (almost
                # always) empty too — admission can't fail with every
                # slot free — so idle blocks untimed on submit()/stop()
                # notifies instead of polling 20x/s per loaded decoder;
                # the timed wait survives only for the defensive case
                # of a non-empty queue, whose deadlines need the poll
                self._cond.wait(0.05 if self._queue else None)

    def _loop(self):
        while True:
            live = self._next_live()
            if live is None:
                return
            try:
                self._step(live)
            except BaseException as e:  # a broken step fails ITS slots
                _log.error("decode step on %s v%d failed: %s: %s",
                           self.name, self.version, type(e).__name__, e)
                err = (e if isinstance(e, ServingError) else
                       ServingError(f"{type(e).__name__}: {e}"))
                with self._cond:
                    for s in live:
                        if not s.req.ev.is_set():
                            self._fail_locked(s.req, err)
                    self._slots = [s for s in self._slots
                                   if s not in live]
                    self._g_live.set(len(self._slots))
                    if self._donate:
                        # the raising step already consumed the donated
                        # pools — k/v are deleted buffers and every
                        # later step would fail too. Retire: fail
                        # everything, refuse new submits (EngineRetired
                        # -> the server resubmits after a redeploy)
                        # instead of admitting doomed requests.
                        _log.error(
                            "decode pools for %s v%d were donated into "
                            "the failed step — retiring the engine",
                            self.name, self.version)
                        self._stopping = True
                        for s in self._slots:
                            if not s.req.ev.is_set():
                                self._fail_locked(s.req, err)
                        self._slots = []
                        for r in self._queue:
                            self._fail_locked(r, err)
                        self._queue.clear()
                        self._g_depth.set(0)
                        self._g_live.set(0)
                        self._cond.notify_all()
                        return

    def _run_step_arrays(self, tokens, positions, q_lens, tables, lens):
        """Shared by warm() and live steps: count a DISTINCT-shape
        compile, run the jitted step, rebind the pools."""
        with self._step_mu:
            key = (len(tokens), tables.shape[1], tokens.shape[1])
            if key not in self._compiled_shapes:
                self._compiled_shapes.add(key)
                _m_compiles.inc()
            k, v, logits = self._step_fn(
                self._params, tokens, positions, q_lens, self.cache.k,
                self.cache.v, tables, lens)
            self.cache.rebind(k, v)
            return logits

    def _prepare(self, live: List[_Slot]
                 ) -> Tuple[List[_Slot], List[int]]:
        """Pre-step phase (scheduler thread, ISSUE 13): execute pending
        COW copies and preemption restores (device writes, batched,
        under ``_step_mu`` — the same serialization every pool touch
        gets), then grow demand-mode reservations to cover this step's
        grants, preempting/demoting when the pool runs dry. Returns the
        (possibly shrunk) live list and its grants."""
        cows: List[Tuple[int, int]] = []
        restores = []
        spills: Dict[int, Any] = {}
        for s in live:
            if s.pending_restore:
                s.pending_restore = False
                # pop (disk-backed spills np.load) stays outside _cond
                spills[s.req.seq_id] = self._spill.pop(s.req.seq_id)
        with self._cond:
            # request state (cow, pages, spill ownership) is mutated by
            # cancel()/_fail_locked under _cond — read it under _cond
            # too, or a mid-window cancel hands us freed pages / a
            # half-released COW
            for s in live:
                if s.req.ev.is_set():
                    # canceled: pages already freed and any spill
                    # dropped; the popped arrays (if any) die here and
                    # the slot rides one last garbage-table step
                    continue
                spill = spills.get(s.req.seq_id)
                if spill is not None:
                    pages = self.cache.allocator.pages_of(s.req.seq_id)
                    restores.append((pages[:spill[0].shape[1]], spill))
                    _m_restores.inc()
                if s.req.cow is not None:
                    cows.append((s.req.cow["src"], s.req.cow["dst"]))
                    # released before the device copy runs: safe, the
                    # scheduler thread issues every device write, so an
                    # evicted-and-reused src page cannot be rewritten
                    # before copy_pages below reads it
                    self.cache.allocator.release_cow(s.req.cow["key"])
                    s.req.cow = None
        if cows or restores:
            with self._step_mu:
                self.cache.copy_pages(cows)
                for pages, (k, v) in restores:
                    self.cache.scatter_pages(pages, k, v)
        while True:
            grants = self._grants(live)
            grower = None
            for s, g in zip(live, grants):
                if s.req.ev.is_set():
                    continue  # canceled: pages gone, rides one last
                    # step through the garbage table, answered nowhere
                need = self.cache.allocator.pages_for_tokens(s.pos + g)
                if need > s.pages_held:
                    grower = (s, need - s.pages_held)
                    break
            if grower is None:
                return live, grants
            s, n = grower
            try:
                self.cache.allocator.grow(s.req.seq_id, n)
                s.pages_held += n
                continue
            except ServerOverloaded:
                pass
            if self._reclaim_for_growth(s, live):
                continue
            # nothing reclaimable: the submit-time worst-case-fits-pool
            # check makes this unreachable unless an external allocator
            # user pins pages — fail typed rather than corrupt
            with self._cond:
                if not s.req.ev.is_set():
                    _m_overloads.inc()
                    self._fail_locked(s.req, ServerOverloaded(
                        f"KV pool exhausted mid-decode for seq "
                        f"{s.req.seq_id} with nothing left to preempt "
                        "— external pages pinned?"))
                self._slots = [x for x in self._slots if x is not s]
                self._g_live.set(len(self._slots))
            live = [x for x in live if x is not s]
            if not live:
                return live, []

    def _reclaim_for_growth(self, grower: _Slot,
                            live: List[_Slot]) -> bool:
        """Make pages available for a live slot's growth: demote the
        newest QUEUED reservation first (it has no computed work to
        lose — admission re-reserves it later), else preempt the
        newest live slot other than the grower (spill + requeue at the
        front). Mutates ``live`` in place when it preempts. False =
        nothing left to take."""
        with self._cond:
            for req in reversed(self._queue):
                if req.ev.is_set() or req.needs_alloc:
                    continue
                self.cache.allocator.free(req.seq_id)
                if req.cow is not None:
                    self.cache.allocator.release_cow(req.cow["key"])
                    req.cow = None
                req.cached_tokens = 0
                req.needs_alloc = True
                _m_demotions.inc()
                return True
        victim = None
        for s in reversed(live):
            if s is grower or s.req.ev.is_set():
                continue
            victim = s
            break
        if victim is None:
            return False
        self._preempt(victim)
        live.remove(victim)
        return True

    def _preempt(self, victim: _Slot):
        """Spill the victim's written pages to host (bitwise), free its
        reservation, and requeue it at the FRONT so preemption cannot
        become starvation. Restore scatters the spill into a fresh
        reservation and the page table rebinds — the sequence's K/V
        round-trips exactly (preempt-never-corrupts; reserve-never-dies
        was the PR 6 policy this replaces)."""
        _faults.fire("serving.decode.preempt")
        req = victim.req
        with _tracing.span("serving.decode.preempt", model=self.name,
                           version=self.version, seq=req.seq_id,
                           tokens=victim.pos):
            pages = self.cache.allocator.pages_of(req.seq_id)
            n_keep = (self.cache.allocator.pages_for_tokens(victim.pos)
                      if victim.pos else 0)
            if n_keep:
                with self._step_mu:
                    k, v = self.cache.gather_pages(pages[:n_keep])
                self._spill.put(req.seq_id, k, v)
            self.cache.allocator.free(req.seq_id)
            _m_preemptions.inc()
            with self._cond:
                self._slots = [x for x in self._slots if x is not victim]
                if req.ev.is_set():
                    # canceled/stopped while we spilled: nothing will
                    # resume — drop the spill, leak nothing
                    self._spill.drop(req.seq_id)
                else:
                    req.resume_pos = victim.pos
                    req.carry_steps = victim.steps
                    req.carry_fts = victim.first_token_steps
                    req.needs_alloc = True
                    self._queue.insert(0, req)
                    self._g_depth.set(len(self._queue))
                self._g_live.set(len(self._slots))

    def _grants(self, live: List[_Slot]) -> List[int]:
        """Token-budget scheduling (Sarathi-style, ISSUE 10): every
        slot past its prompt gets its one decode token unconditionally
        — in-flight decodes NEVER stall behind a prompt — while slots
        still in prefill share a per-step budget of ``prefill_chunk``
        prompt tokens, granted in slot order. Every prefill slot is
        guaranteed at least one token per step (at ``prefill_chunk=1``
        this is bitwise the PR 6 one-token-per-slot schedule; no slot
        ever starves), so the budget caps the CHUNKS, not progress. A
        solo prompt takes the whole budget every step: P prompt tokens
        cost ceil(P / prefill_chunk) steps instead of P."""
        budget = self._prefill_chunk
        grants = []
        for s in live:
            remaining_prompt = len(s.req.prompt) - s.pos
            if remaining_prompt > 0:
                g = max(1, min(remaining_prompt, budget))
                budget = max(0, budget - g)
            else:
                g = 1
            grants.append(g)
        return grants

    def _step(self, live: List[_Slot]):
        # named chaos seam for the SCHEDULER cadence: a
        # `delay@serving.decode.step:*=0.004` plan simulates a slow
        # decoder (long-context model, contended chip) so streaming/
        # failover tests can pin mid-generation behavior without racing
        # a fast engine; `error@` fails the step's slots like any other
        # step failure. Zero cost with no plan installed.
        _faults.fire("serving.decode.step")
        # restore-before-step, COW copies, demand-mode growth (may
        # preempt/demote — the returned live list is authoritative)
        live, grants = self._prepare(live)
        if not live:
            return
        s_bucket = _bucket_for(self._slot_ladder, len(live))
        w_need = max(s.pages_held for s in live)
        w_bucket = _bucket_for(self._width_ladder, w_need)
        # pure-decode steps (and 1-token prefill tails) ride the C=1
        # shapes — exactly the PR 6 step; only steps carrying a real
        # chunk pay the chunk-wide compute
        c_bucket = _bucket_for(self._chunk_ladder, max(max(grants), 1))
        prefill_toks = sum(g for s, g in zip(live, grants)
                           if s.pos < len(s.req.prompt))
        tokens = np.zeros((s_bucket, c_bucket), np.int32)
        positions = np.zeros((s_bucket, c_bucket), np.int32)
        q_lens = np.zeros(s_bucket, np.int32)
        lens = np.zeros(s_bucket, np.int32)
        for i, (s, g) in enumerate(zip(live, grants)):
            for j in range(g):
                tokens[i, j] = s.token_at(s.pos + j)
                positions[i, j] = s.pos + j
            q_lens[i] = g
            # keys INCLUDING this chunk; within it, query j attends
            # only keys up to its own position (chunk-causal)
            lens[i] = s.pos + g
            # the reservation (grown by _prepare in demand mode) must
            # cover every write this step performs. A real raise, not
            # an assert: writing through a page index past the
            # reservation would corrupt another sequence's pages, and
            # `python -O` strips asserts. Canceled slots are exempt —
            # their pages are gone and their table row is all-garbage,
            # so their writes land on the garbage page by construction
            if not s.req.ev.is_set() and \
                    lens[i] > s.pages_held * self.cache.page_size:
                raise ServingError(
                    f"chunk grant escaped seq {s.req.seq_id}'s page "
                    f"reservation ({lens[i]} tokens > "
                    f"{s.pages_held} pages x {self.cache.page_size})")
        tables = self.cache.table_array(
            [s.req.seq_id for s in live], w_bucket, rows=s_bucket)
        t0 = time.perf_counter()
        # one decode step joins the OLDEST live request's trace (a span
        # has one parent); per-slot request spans live in the server
        with _tracing.adopt(live[0].req.trace_ctx), \
                _tracing.span("serving.decode.step", model=self.name,
                              version=self.version, slots=s_bucket,
                              width=w_bucket, chunk=c_bucket,
                              prefill_tokens=prefill_toks,
                              live=len(live)):
            logits = self._run_step_arrays(tokens, positions, q_lens,
                                           tables, lens)
        logits_np = np.asarray(logits)   # [B, vocab] — newest lane only
        # the greedy fast path for the whole batch; per-request sampling
        # policies (temperature/top_k/seed) resolve per slot below
        sampled = np.asarray(np.argmax(logits_np, axis=-1))  # [B]
        _m_step_ms.observe((time.perf_counter() - t0) * 1e3)
        _m_steps.inc()
        _m_occupancy.observe(len(live) / float(s_bucket))
        # prices the token-budget policy next to occupancy: how much of
        # each step's budget real prefill work consumed
        _m_prefill_per_step.observe(prefill_toks)
        if prefill_toks:
            _m_prefill_tokens.inc(prefill_toks)
        with self._cond:
            self._n_steps += 1
        now = time.monotonic()
        done: List[_Slot] = []
        # the whole answer phase holds _cond: stop(drain=False) fails
        # requests under _cond, so check-ev-then-answer must be atomic
        # with it or the two sides can each answer the same request
        notes: Dict[int, int] = {}
        produced_any = False
        with self._cond:
            for i, s in enumerate(live):
                if s.req.ev.is_set():
                    # already answered — stop(drain=False) raced this
                    # step and failed the request; don't double-answer
                    # or count a completion/token for it
                    done.append(s)
                    continue
                g = grants[i]        # >= 1: every live slot progresses
                s.steps += 1
                s.pos += g
                notes[s.req.seq_id] = s.pos
                if self._prefix_on and not s.req.published and \
                        s.pos >= len(s.req.prompt):
                    # prompt K/V fully on-device as of THIS step:
                    # publish the prompt pages into the prefix index
                    # (metadata only; from here they are immutable —
                    # this sequence only ever writes PAST them, and
                    # they outlive its free() as the shared cache)
                    self.cache.allocator.publish(s.req.seq_id,
                                                 s.req.prompt)
                    s.req.published = True
                tok = None
                if s.pos >= len(s.req.prompt):
                    # logits_np[i] is the slot's newest lane (the step
                    # unembeds only lane q_len-1): prompt token P-1
                    # when the chunk just finished prefill, else the
                    # decode token. s.pos is the new token's absolute
                    # index in its sequence — the (seed, position) pair
                    # that makes sampling independent of batch
                    # composition AND of chunking
                    tok = (int(sampled[i])
                           if s.req.temperature <= 0.0
                           else sample_token(
                               logits_np[i], s.req.temperature,
                               s.req.top_k, s.req.seed, s.pos))
                    s.req.produced.append(tok)
                    produced_any = True
                    _m_tokens.inc()
                    if s.first_token_steps is None:
                        s.first_token_steps = s.steps
                        _m_first_token_steps.observe(s.steps)
                finished = (len(s.req.produced) >= s.req.max_new
                            or (tok is not None
                                and self.spec.eos_id is not None
                                and tok == self.spec.eos_id))
                if finished:
                    # finished beats a lapsed deadline: the result is
                    # fully paid for — deliver it rather than discard
                    done.append(s)
                    self._complete(s)
                elif s.req.deadline is not None and now > s.req.deadline:
                    _m_deadline_miss.inc()
                    done.append(s)
                    self._fail_locked(s.req, DeadlineExceeded(
                        f"request to decoder '{self.name}' lapsed "
                        f"mid-decode after {len(s.req.produced)} tokens"))
            # one allocator-lock round-trip for the whole step; seqs
            # freed by _complete/_fail above are skipped inside
            self.cache.allocator.note_tokens_many(notes)
            if done:
                self._slots = [s for s in self._slots if s not in done]
                self._g_live.set(len(self._slots))
            if done or produced_any:
                # wake completion waiters AND streaming readers parked
                # in stream_tokens — a token exists the moment this
                # notify lands, ceil(prompt/chunk) steps after
                # admission, not when the whole sequence finishes
                self._cond.notify_all()

    def _complete(self, s: _Slot):
        self.cache.allocator.free(s.req.seq_id)
        _m_completions.inc()
        _m_total.observe((time.monotonic() - s.req.t_enq) * 1e3)
        s.req.result = {
            "tokens": list(s.req.produced),
            "prompt_len": int(len(s.req.prompt)),
            "version": self.version,
            # scheduler steps from admission to the first generated
            # token — the load-independent chunked-prefill evidence
            # (ceil(P/chunk) + co-riding, vs P unchunked; for a
            # prefix-cache hit, suffix takes the prompt's place:
            # ceil((P - cached)/chunk))
            "steps_to_first_token": int(s.first_token_steps or s.steps),
            # prompt tokens answered from the prefix index instead of
            # prefilled (0 = cold)
            "cached_tokens": int(s.req.cached_tokens),
        }
        s.req.ev.set()
